"""Block-shipped learning: streaming, delta-aware SST transfer (ISSUE 13).

The learn/rebalance/bootstrap plane's shared machinery — replacing the
monolithic "read every checkpoint file into one dict under the primary's
lock" re-seed with a manifest-diff handshake plus chunked block
streaming (the RDMA index-replication shape from PAPERS.md: ship
compacted engine state and replay only the log tail):

  1. the learner sends its live SST set (filename + content digest);
  2. the primary pins an immutable checkpoint (checkpoint GC and plog GC
     of covered segments are held while pinned — TTL leases, so a dead
     learner can never wedge GC forever) and replies with the full block
     manifest plus which blocks the learner is missing;
  3. the learner stages blocks into ``learn_ckpt/``: already-staged
     blocks from an interrupted ship and digest-matching live files are
     reused (delta + resume at block granularity), the rest stream as
     bounded chunks with a per-chunk CRC over the existing ``call_many``
     wave machinery, and every landed block re-verifies its whole-file
     digest before it counts;
  4. the swap into the serving engine happens in a short critical
     section, after the staged state proved itself byte-consistent via
     the PR 8 decree-anchored digest compared at the checkpoint decree.

Three "copy a partition" flows ride this one implementation: learner
re-seed (replication/replica.py), the meta balancer's add-secondary path
(which seeds over the same learn RPC surface), and duplicator bootstrap
of a fresh remote cluster (replication/bootstrap.py).

Counters (learner-side, so the replay-vs-ship win is measurable on CPU):
``learn.ship.{blocks,bytes,duration_us,delta_skipped_blocks}`` and
``learn.replay.mutations``.
"""

import hashlib
import json
import os
import zlib

from ..rpc import codec
from ..rpc import messages as rpc_msg
from ..rpc.transport import RpcError
from ..runtime.fail_points import inject
from ..runtime.perf_counters import counters


def _warm_verify_counters() -> None:
    """Pre-register the arrival-proof counters (zeros before the first
    learn; the chaos/satellite tests counter-assert against them)."""
    counters.rate("learn.verify.incremental_count")
    counters.rate("learn.verify.rescan_count")


_warm_verify_counters()


class LearnShipError(ConnectionError):
    """A block ship failed (chunk CRC, digest mismatch, expired pin).
    ConnectionError subclass: every learn caller already treats peer
    ConnectionErrors as "this learn failed, retry later"."""


def chunk_bytes() -> int:
    """PEGASUS_LEARN_CHUNK_BYTES: bounded block-streaming chunk size."""
    return max(4096, int(os.environ.get("PEGASUS_LEARN_CHUNK_BYTES",
                                        str(1 << 20))))


def delta_enabled() -> bool:
    """PEGASUS_LEARN_DELTA=0 is the delta kill switch: every learn ships
    the full checkpoint (the streaming/resume machinery still applies)."""
    return os.environ.get("PEGASUS_LEARN_DELTA", "1") != "0"


def verify_enabled() -> bool:
    """PEGASUS_LEARN_VERIFY=0 skips the decree-anchored digest proof on
    arrival (the per-chunk CRC + per-block digest checks always run)."""
    return os.environ.get("PEGASUS_LEARN_VERIFY", "1") != "0"


def pin_ttl_s() -> float:
    """PEGASUS_LEARN_PIN_TTL_S: checkpoint/log pin lease per learn;
    renewed by fetch activity, so it bounds learner DEATH, not learn
    duration."""
    return float(os.environ.get("PEGASUS_LEARN_PIN_TTL_S", "600"))


def incremental_digest_enabled() -> bool:
    """PEGASUS_LEARN_INCREMENTAL_DIGEST=0 forces the learner's arrival
    proof back to the full staged-state rescan (the incremental
    per-block fold is the default — O(delta), see manifest_fold)."""
    return os.environ.get("PEGASUS_LEARN_INCREMENTAL_DIGEST", "1") != "0"


def manifest_fold(entries) -> str:
    """Commutative fold over a block manifest's (name, digest) pairs —
    the incremental staged-state digest (ISSUE 14 satellite, learn
    follow-on c). ``stage_blocks`` maintains the same fold over the
    blocks it VERIFIED; since every staging path verifies against the
    manifest's digest, equality with the manifest fold is a
    COMPLETENESS invariant (every manifest entry went through a
    verification path — a future staging edit that skips one breaks the
    fold), not an independent re-derivation of the bytes. The per-block
    integrity itself comes from the stage-time checks: fetched blocks
    hash on landing, reused blocks hash (or share the just-hashed
    inode), and previously-verified blocks are trusted via the
    sidecar's stat identity — the O(delta) contract's one residual
    trust window (an in-place rewrite that preserves size AND mtime_ns
    evades it; ``PEGASUS_LEARN_INCREMENTAL_DIGEST=0`` restores the full
    record-level rescan for deployments that cannot accept that). XOR +
    additive-sum of a crc64 per entry, the state_digest combine shape,
    so block order cannot matter."""
    from ..base.crc64 import crc64

    xor = add = 0
    for e in entries:
        name = e["name"] if isinstance(e, dict) else e[0]
        digest = e["digest"] if isinstance(e, dict) else e[1]
        c = crc64(name.encode() + b"\x00" + digest.encode())
        xor ^= c
        add = (add + c) & 0xFFFFFFFFFFFFFFFF
    return f"{xor:016x}{add:016x}"


def chunk_waves(total: int, chunk: int, wave_bytes: int = 8 << 20):
    """Yield bounded waves of (offset, length) descriptors covering a
    `total`-byte block — the ONE chunk grid under every chunked
    transfer plane (learn fetch, offload ship, offload fetch): each
    wave's in-flight byte volume stays under `wave_bytes`, and a
    zero-byte block still yields its single empty-chunk descriptor."""
    offs = list(range(0, total, chunk)) or [0]
    per = max(1, wave_bytes // chunk)
    for i in range(0, len(offs), per):
        yield [(off, min(chunk, max(0, total - off)))
               for off in offs[i:i + per]]


def file_digest(path: str) -> str:
    """Content digest for block identity (md5: C-speed streaming; this
    is a transfer-dedup key, not a security boundary — corruption on the
    wire is caught by the per-chunk CRC and this digest together)."""
    h = hashlib.md5()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def dir_manifest(dirpath: str, suffix: str = None) -> list:
    """[{"name", "size", "digest"}] for the regular files in `dirpath`
    (optionally only names ending with `suffix`), sorted by name.
    Vanishing files (a live engine unlinking mid-scan) are skipped —
    the manifest is a best-effort "what do I already hold" set."""
    out = []
    if not os.path.isdir(dirpath):
        return out
    for name in sorted(os.listdir(dirpath)):
        if suffix is not None and not name.endswith(suffix):
            continue
        if name.endswith(".part"):
            continue  # torn partial from an interrupted ship
        if name.startswith("."):
            continue  # sidecar state (.staged.json), never a block
        p = os.path.join(dirpath, name)
        try:
            if not os.path.isfile(p):
                continue
            out.append({"name": name, "size": os.path.getsize(p),
                        "digest": file_digest(p)})
        except OSError:
            continue
    return out


_SIDECAR = ".staged.json"


def _load_sidecar(dest_dir: str) -> dict:
    """{name: {"digest", "size", "mtime_ns"}} of blocks a PRIOR
    stage_blocks verified into `dest_dir` — the O(1) resume check: a
    stat match against the recorded identity replaces the md5 rescan,
    a mismatch falls back to hashing. The stat identity is a TRUST
    decision (see manifest_fold's docstring for the window it leaves);
    PEGASUS_LEARN_INCREMENTAL_DIGEST=0 removes it entirely."""
    try:
        with open(os.path.join(dest_dir, _SIDECAR)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _save_sidecar(dest_dir: str, entries: dict) -> None:
    tmp = os.path.join(dest_dir, _SIDECAR + ".tmp")
    try:
        with open(tmp, "w") as f:
            json.dump(entries, f)
        os.replace(tmp, os.path.join(dest_dir, _SIDECAR))
    except OSError:
        pass  # best-effort: a lost sidecar just re-hashes next learn


def _stat_entry(path: str, digest: str) -> dict:
    st = os.stat(path)
    return {"digest": digest, "size": st.st_size,
            "mtime_ns": st.st_mtime_ns}


def _link_or_copy(src: str, dst: str) -> None:
    import shutil

    if os.path.exists(dst):
        os.unlink(dst)
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)


def _fetch_block(source, learn_id: int, entry: dict, dest_dir: str) -> int:
    """Stream one block from the source as bounded chunks (per-chunk
    CRC), land it atomically (.part + rename) after the whole-file
    digest matched the manifest entry. -> bytes fetched."""
    inject("learn.ship")  # chaos seam: a mid-ship abort on the learner
    name, total = entry["name"], entry["size"]
    part = os.path.join(dest_dir, name + ".part")
    fetched = 0
    # one RPC round per bounded wave: pipelined over call_many for an
    # RPC source, a plain loop for an in-process one — either way the
    # in-flight byte volume stays wave-bounded (chunk_waves)
    with open(part, "wb") as f:
        for wave in chunk_waves(total, chunk_bytes()):
            reqs = [(name, off, ln) for off, ln in wave]
            chunks = source.fetch_learn_chunks(learn_id, reqs)
            for (_, off, ln), ch in zip(reqs, chunks):
                data = ch["data"]
                if len(data) != ln or zlib.crc32(data) != ch["crc"]:
                    raise LearnShipError(
                        f"chunk CRC/length mismatch for {name}@{off}")
                f.write(data)
                fetched += len(data)
    if file_digest(part) != entry["digest"]:
        os.unlink(part)
        raise LearnShipError(f"shipped block {name} digest mismatch")
    os.replace(part, os.path.join(dest_dir, name))
    return fetched


def stage_blocks(source, st: dict, dest_dir: str, reuse: dict = None,
                 delta: bool = None) -> dict:
    """Materialize the learn manifest ``st["blocks"]`` into `dest_dir`,
    exactly: already-staged blocks whose digest matches are kept
    (resume), digest-matching local files from `reuse` ({digest: path},
    built by the caller from its ALREADY-computed have-manifest — no
    second directory scan) are hardlinked in (delta skip), everything
    else streams from `source` in CRC-checked chunks. delta=False (the
    PEGASUS_LEARN_DELTA kill switch) disables BOTH reuse and resume:
    every block re-fetches from the source. Files not in the manifest
    are pruned, so the staged dir is swap-ready. -> stats dict."""
    os.makedirs(dest_dir, exist_ok=True)
    delta = delta_enabled() if delta is None else bool(delta)
    stats = {"blocks": len(st["blocks"]), "fetched": 0, "bytes": 0,
             "skipped": 0, "resumed": 0}
    reuse = dict(reuse or {}) if delta else {}
    # the sidecar records every block a prior stage VERIFIED (digest +
    # stat identity): an untouched staged block resumes on a stat match
    # — no re-hash — which is what makes the whole stage, and the
    # arrival proof built on its fold, O(delta) per learn
    sidecar = _load_sidecar(dest_dir) if delta else {}
    verified = []  # (name, digest) pairs proven this stage -> stats["fold"]
    want = {e["name"] for e in st["blocks"]}
    for name in os.listdir(dest_dir):
        if name not in want and not name.startswith("."):
            sidecar.pop(name, None)
            try:
                os.unlink(os.path.join(dest_dir, name))
            except OSError:
                pass
    c_blocks = counters.rate("learn.ship.blocks")
    c_bytes = counters.rate("learn.ship.bytes")
    c_skip = counters.rate("learn.ship.delta_skipped_blocks")
    try:
        for entry in st["blocks"]:
            dst = os.path.join(dest_dir, entry["name"])
            if delta:
                side = sidecar.get(entry["name"])
                try:
                    if side is not None and side["digest"] == entry["digest"] \
                            and _stat_entry(dst, entry["digest"]) == side:
                        # sidecar fast path: identity unchanged since the
                        # last verified stage — O(1), no re-hash
                        stats["resumed"] += 1
                        verified.append((entry["name"], entry["digest"]))
                        c_skip.increment()
                        continue
                except OSError:
                    pass
                try:
                    if os.path.isfile(dst) \
                            and file_digest(dst) == entry["digest"]:
                        stats["resumed"] += 1  # staged by an interrupted ship
                        sidecar[entry["name"]] = _stat_entry(
                            dst, entry["digest"])
                        verified.append((entry["name"], entry["digest"]))
                        c_skip.increment()
                        continue
                except OSError:
                    pass
                src = reuse.get(entry["digest"])
                if src is not None:
                    try:
                        _link_or_copy(src, dst)
                        # a HARDLINK shares the source inode, whose digest
                        # the caller's have-manifest just computed — the
                        # O(n) re-hash of every reused block is only
                        # needed when the link degraded to a copy
                        same_inode = os.stat(dst).st_ino == \
                            os.stat(src).st_ino
                        if same_inode or file_digest(dst) == entry["digest"]:
                            stats["skipped"] += 1  # delta: learner had it
                            sidecar[entry["name"]] = _stat_entry(
                                dst, entry["digest"])
                            verified.append((entry["name"], entry["digest"]))
                            c_skip.increment()
                            continue
                        os.unlink(dst)
                    except OSError:
                        pass  # vanished under us: stream it instead
            stats["bytes"] += _fetch_block(source, st["learn_id"], entry,
                                           dest_dir)
            stats["fetched"] += 1
            sidecar[entry["name"]] = _stat_entry(dst, entry["digest"])
            verified.append((entry["name"], entry["digest"]))
            c_blocks.increment()
    finally:
        # partial progress persists: an aborted ship's retry resumes
        # against what landed (the sidecar only ever names VERIFIED
        # blocks, so a torn write can't be trusted by mistake)
        _save_sidecar(dest_dir, sidecar)
    c_bytes.increment(stats["bytes"])
    # the incremental staged-state digest: fold of exactly the verified
    # set — equals manifest_fold(st["blocks"]) iff the staged dir holds
    # the checkpoint's bytes, block for block
    stats["fold"] = manifest_fold(verified)
    return stats


class RemoteLearnSource:
    """Learn-protocol client over the RPC transport — the one
    implementation behind ``_RemotePeer``'s learn surface and the
    duplicator bootstrap. Chunk fetches pipeline through ``call_many``
    (one coalesced send per wave)."""

    def __init__(self, pool, addr: str, app_id: int, pidx: int,
                 timeout: float = 30.0):
        self.pool = pool
        self.addr = addr
        self.app_id = app_id
        self.pidx = pidx
        self.timeout = timeout

    def _conn(self):
        host, _, port = self.addr.rpartition(":")
        return self.pool.get((host, int(port)),
                             shard=("rep", self.app_id, self.pidx))

    def _call(self, code: str, req, resp_cls):
        try:
            _, body = self._conn().call(
                code, codec.encode(req), app_id=self.app_id,
                partition_index=self.pidx, timeout=self.timeout)
        except (RpcError, OSError) as e:
            raise ConnectionError(str(e))
        resp = codec.decode(resp_cls, body)
        if resp.error:
            raise LearnShipError(f"{code} failed: {resp.error_text}")
        return resp

    def prepare_learn_state(self, have=None, delta=None) -> dict:
        from .replica_stub import RPC_LEARN_PREPARE
        from ..runtime.job_trace import JOB_TRACER

        req = rpc_msg.LearnPrepareRequest(
            app_id=self.app_id, pidx=self.pidx,
            delta=delta_enabled() if delta is None else bool(delta),
            have=[rpc_msg.LearnBlockEntry(e["name"], e["size"], e["digest"])
                  for e in (have or [])],
            # the learn job's trace id (ISSUE 16): the serving primary
            # attributes its checkpoint pin to this learn's timeline
            job=JOB_TRACER.current() or "")
        resp = self._call(RPC_LEARN_PREPARE, req,
                          rpc_msg.LearnPrepareResponse)
        return {
            "learn_id": resp.learn_id, "ckpt_decree": resp.ckpt_decree,
            "ballot": resp.ballot, "last_committed": resp.last_committed,
            "blocks": [{"name": e.name, "size": e.size, "digest": e.digest}
                       for e in resp.blocks],
            "missing": list(resp.missing), "digest": resp.digest,
            "digest_now": resp.digest_now, "digest_pmask": resp.digest_pmask,
        }

    def fetch_learn_chunks(self, learn_id: int, reqs) -> list:
        from .replica_stub import RPC_LEARN_FETCH

        calls = [(RPC_LEARN_FETCH,
                  codec.encode(rpc_msg.LearnFetchRequest(
                      app_id=self.app_id, pidx=self.pidx, learn_id=learn_id,
                      name=name, offset=off, length=ln)),
                  self.app_id, self.pidx, 0) for (name, off, ln) in reqs]
        try:
            results = self._conn().call_many(calls, timeout=self.timeout)
        except (RpcError, OSError) as e:
            raise ConnectionError(str(e))
        out = []
        for _, body in results:
            resp = codec.decode(rpc_msg.LearnFetchResponse, body)
            if resp.error:
                raise LearnShipError(f"learn fetch failed: {resp.error_text}")
            out.append({"data": resp.data, "crc": resp.crc,
                        "total": resp.total})
        return out

    def fetch_learn_tail(self, learn_id: int) -> dict:
        from .mutation_log import LogMutation
        from .replica_stub import RPC_LEARN_TAIL

        resp = self._call(RPC_LEARN_TAIL,
                          rpc_msg.LearnTailRequest(
                              app_id=self.app_id, pidx=self.pidx,
                              learn_id=learn_id),
                          rpc_msg.LearnTailResponse)
        return {"tail": [codec.decode(LogMutation, t) for t in resp.tail],
                "last_committed": resp.last_committed, "ballot": resp.ballot}

    def finish_learn(self, learn_id: int) -> None:
        from .replica_stub import RPC_LEARN_FINISH

        try:
            self._call(RPC_LEARN_FINISH,
                       rpc_msg.LearnFinishRequest(
                           app_id=self.app_id, pidx=self.pidx,
                           learn_id=learn_id),
                       rpc_msg.LearnFetchResponse)
        except (ConnectionError, LearnShipError):
            pass  # pin TTL covers an unreachable primary
