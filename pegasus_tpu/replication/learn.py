"""Block-shipped learning: streaming, delta-aware SST transfer (ISSUE 13).

The learn/rebalance/bootstrap plane's shared machinery — replacing the
monolithic "read every checkpoint file into one dict under the primary's
lock" re-seed with a manifest-diff handshake plus chunked block
streaming (the RDMA index-replication shape from PAPERS.md: ship
compacted engine state and replay only the log tail):

  1. the learner sends its live SST set (filename + content digest);
  2. the primary pins an immutable checkpoint (checkpoint GC and plog GC
     of covered segments are held while pinned — TTL leases, so a dead
     learner can never wedge GC forever) and replies with the full block
     manifest plus which blocks the learner is missing;
  3. the learner stages blocks into ``learn_ckpt/``: already-staged
     blocks from an interrupted ship and digest-matching live files are
     reused (delta + resume at block granularity), the rest stream as
     bounded chunks with a per-chunk CRC over the existing ``call_many``
     wave machinery, and every landed block re-verifies its whole-file
     digest before it counts;
  4. the swap into the serving engine happens in a short critical
     section, after the staged state proved itself byte-consistent via
     the PR 8 decree-anchored digest compared at the checkpoint decree.

Three "copy a partition" flows ride this one implementation: learner
re-seed (replication/replica.py), the meta balancer's add-secondary path
(which seeds over the same learn RPC surface), and duplicator bootstrap
of a fresh remote cluster (replication/bootstrap.py).

Counters (learner-side, so the replay-vs-ship win is measurable on CPU):
``learn.ship.{blocks,bytes,duration_us,delta_skipped_blocks}`` and
``learn.replay.mutations``.
"""

import hashlib
import os
import zlib

from ..rpc import codec
from ..rpc import messages as rpc_msg
from ..rpc.transport import RpcError
from ..runtime.fail_points import inject
from ..runtime.perf_counters import counters


class LearnShipError(ConnectionError):
    """A block ship failed (chunk CRC, digest mismatch, expired pin).
    ConnectionError subclass: every learn caller already treats peer
    ConnectionErrors as "this learn failed, retry later"."""


def chunk_bytes() -> int:
    """PEGASUS_LEARN_CHUNK_BYTES: bounded block-streaming chunk size."""
    return max(4096, int(os.environ.get("PEGASUS_LEARN_CHUNK_BYTES",
                                        str(1 << 20))))


def delta_enabled() -> bool:
    """PEGASUS_LEARN_DELTA=0 is the delta kill switch: every learn ships
    the full checkpoint (the streaming/resume machinery still applies)."""
    return os.environ.get("PEGASUS_LEARN_DELTA", "1") != "0"


def verify_enabled() -> bool:
    """PEGASUS_LEARN_VERIFY=0 skips the decree-anchored digest proof on
    arrival (the per-chunk CRC + per-block digest checks always run)."""
    return os.environ.get("PEGASUS_LEARN_VERIFY", "1") != "0"


def pin_ttl_s() -> float:
    """PEGASUS_LEARN_PIN_TTL_S: checkpoint/log pin lease per learn;
    renewed by fetch activity, so it bounds learner DEATH, not learn
    duration."""
    return float(os.environ.get("PEGASUS_LEARN_PIN_TTL_S", "600"))


def file_digest(path: str) -> str:
    """Content digest for block identity (md5: C-speed streaming; this
    is a transfer-dedup key, not a security boundary — corruption on the
    wire is caught by the per-chunk CRC and this digest together)."""
    h = hashlib.md5()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def dir_manifest(dirpath: str, suffix: str = None) -> list:
    """[{"name", "size", "digest"}] for the regular files in `dirpath`
    (optionally only names ending with `suffix`), sorted by name.
    Vanishing files (a live engine unlinking mid-scan) are skipped —
    the manifest is a best-effort "what do I already hold" set."""
    out = []
    if not os.path.isdir(dirpath):
        return out
    for name in sorted(os.listdir(dirpath)):
        if suffix is not None and not name.endswith(suffix):
            continue
        if name.endswith(".part"):
            continue  # torn partial from an interrupted ship
        p = os.path.join(dirpath, name)
        try:
            if not os.path.isfile(p):
                continue
            out.append({"name": name, "size": os.path.getsize(p),
                        "digest": file_digest(p)})
        except OSError:
            continue
    return out


def _link_or_copy(src: str, dst: str) -> None:
    import shutil

    if os.path.exists(dst):
        os.unlink(dst)
    try:
        os.link(src, dst)
    except OSError:
        shutil.copy2(src, dst)


def _fetch_block(source, learn_id: int, entry: dict, dest_dir: str) -> int:
    """Stream one block from the source as bounded chunks (per-chunk
    CRC), land it atomically (.part + rename) after the whole-file
    digest matched the manifest entry. -> bytes fetched."""
    inject("learn.ship")  # chaos seam: a mid-ship abort on the learner
    name, total = entry["name"], entry["size"]
    cb = chunk_bytes()
    offs = list(range(0, total, cb)) or [0]
    part = os.path.join(dest_dir, name + ".part")
    fetched = 0
    # one wave per bounded group of chunks: pipelined over call_many for
    # an RPC source, a plain loop for an in-process one — either way the
    # in-flight byte volume stays bounded by wave_chunks * chunk_bytes
    wave_chunks = max(1, (8 << 20) // cb)
    with open(part, "wb") as f:
        for i in range(0, len(offs), wave_chunks):
            reqs = [(name, off, min(cb, max(0, total - off)))
                    for off in offs[i:i + wave_chunks]]
            chunks = source.fetch_learn_chunks(learn_id, reqs)
            for (_, off, ln), ch in zip(reqs, chunks):
                data = ch["data"]
                if len(data) != ln or zlib.crc32(data) != ch["crc"]:
                    raise LearnShipError(
                        f"chunk CRC/length mismatch for {name}@{off}")
                f.write(data)
                fetched += len(data)
    if file_digest(part) != entry["digest"]:
        os.unlink(part)
        raise LearnShipError(f"shipped block {name} digest mismatch")
    os.replace(part, os.path.join(dest_dir, name))
    return fetched


def stage_blocks(source, st: dict, dest_dir: str, reuse: dict = None,
                 delta: bool = None) -> dict:
    """Materialize the learn manifest ``st["blocks"]`` into `dest_dir`,
    exactly: already-staged blocks whose digest matches are kept
    (resume), digest-matching local files from `reuse` ({digest: path},
    built by the caller from its ALREADY-computed have-manifest — no
    second directory scan) are hardlinked in (delta skip), everything
    else streams from `source` in CRC-checked chunks. delta=False (the
    PEGASUS_LEARN_DELTA kill switch) disables BOTH reuse and resume:
    every block re-fetches from the source. Files not in the manifest
    are pruned, so the staged dir is swap-ready. -> stats dict."""
    os.makedirs(dest_dir, exist_ok=True)
    delta = delta_enabled() if delta is None else bool(delta)
    stats = {"blocks": len(st["blocks"]), "fetched": 0, "bytes": 0,
             "skipped": 0, "resumed": 0}
    reuse = dict(reuse or {}) if delta else {}
    want = {e["name"] for e in st["blocks"]}
    for name in os.listdir(dest_dir):
        if name not in want:
            try:
                os.unlink(os.path.join(dest_dir, name))
            except OSError:
                pass
    c_blocks = counters.rate("learn.ship.blocks")
    c_bytes = counters.rate("learn.ship.bytes")
    c_skip = counters.rate("learn.ship.delta_skipped_blocks")
    for entry in st["blocks"]:
        dst = os.path.join(dest_dir, entry["name"])
        if delta:
            try:
                if os.path.isfile(dst) \
                        and file_digest(dst) == entry["digest"]:
                    stats["resumed"] += 1  # staged by an interrupted ship
                    c_skip.increment()
                    continue
            except OSError:
                pass
            src = reuse.get(entry["digest"])
            if src is not None:
                try:
                    _link_or_copy(src, dst)
                    if file_digest(dst) == entry["digest"]:
                        stats["skipped"] += 1  # delta: learner had it
                        c_skip.increment()
                        continue
                    os.unlink(dst)
                except OSError:
                    pass  # vanished under us: stream it instead
        stats["bytes"] += _fetch_block(source, st["learn_id"], entry,
                                       dest_dir)
        stats["fetched"] += 1
        c_blocks.increment()
    c_bytes.increment(stats["bytes"])
    return stats


class RemoteLearnSource:
    """Learn-protocol client over the RPC transport — the one
    implementation behind ``_RemotePeer``'s learn surface and the
    duplicator bootstrap. Chunk fetches pipeline through ``call_many``
    (one coalesced send per wave)."""

    def __init__(self, pool, addr: str, app_id: int, pidx: int,
                 timeout: float = 30.0):
        self.pool = pool
        self.addr = addr
        self.app_id = app_id
        self.pidx = pidx
        self.timeout = timeout

    def _conn(self):
        host, _, port = self.addr.rpartition(":")
        return self.pool.get((host, int(port)),
                             shard=("rep", self.app_id, self.pidx))

    def _call(self, code: str, req, resp_cls):
        try:
            _, body = self._conn().call(
                code, codec.encode(req), app_id=self.app_id,
                partition_index=self.pidx, timeout=self.timeout)
        except (RpcError, OSError) as e:
            raise ConnectionError(str(e))
        resp = codec.decode(resp_cls, body)
        if resp.error:
            raise LearnShipError(f"{code} failed: {resp.error_text}")
        return resp

    def prepare_learn_state(self, have=None, delta=None) -> dict:
        from .replica_stub import RPC_LEARN_PREPARE

        req = rpc_msg.LearnPrepareRequest(
            app_id=self.app_id, pidx=self.pidx,
            delta=delta_enabled() if delta is None else bool(delta),
            have=[rpc_msg.LearnBlockEntry(e["name"], e["size"], e["digest"])
                  for e in (have or [])])
        resp = self._call(RPC_LEARN_PREPARE, req,
                          rpc_msg.LearnPrepareResponse)
        return {
            "learn_id": resp.learn_id, "ckpt_decree": resp.ckpt_decree,
            "ballot": resp.ballot, "last_committed": resp.last_committed,
            "blocks": [{"name": e.name, "size": e.size, "digest": e.digest}
                       for e in resp.blocks],
            "missing": list(resp.missing), "digest": resp.digest,
            "digest_now": resp.digest_now, "digest_pmask": resp.digest_pmask,
        }

    def fetch_learn_chunks(self, learn_id: int, reqs) -> list:
        from .replica_stub import RPC_LEARN_FETCH

        calls = [(RPC_LEARN_FETCH,
                  codec.encode(rpc_msg.LearnFetchRequest(
                      app_id=self.app_id, pidx=self.pidx, learn_id=learn_id,
                      name=name, offset=off, length=ln)),
                  self.app_id, self.pidx, 0) for (name, off, ln) in reqs]
        try:
            results = self._conn().call_many(calls, timeout=self.timeout)
        except (RpcError, OSError) as e:
            raise ConnectionError(str(e))
        out = []
        for _, body in results:
            resp = codec.decode(rpc_msg.LearnFetchResponse, body)
            if resp.error:
                raise LearnShipError(f"learn fetch failed: {resp.error_text}")
            out.append({"data": resp.data, "crc": resp.crc,
                        "total": resp.total})
        return out

    def fetch_learn_tail(self, learn_id: int) -> dict:
        from .mutation_log import LogMutation
        from .replica_stub import RPC_LEARN_TAIL

        resp = self._call(RPC_LEARN_TAIL,
                          rpc_msg.LearnTailRequest(
                              app_id=self.app_id, pidx=self.pidx,
                              learn_id=learn_id),
                          rpc_msg.LearnTailResponse)
        return {"tail": [codec.decode(LogMutation, t) for t in resp.tail],
                "last_committed": resp.last_committed, "ballot": resp.ballot}

    def finish_learn(self, learn_id: int) -> None:
        from .replica_stub import RPC_LEARN_FINISH

        try:
            self._call(RPC_LEARN_FINISH,
                       rpc_msg.LearnFinishRequest(
                           app_id=self.app_id, pidx=self.pidx,
                           learn_id=learn_id),
                       rpc_msg.LearnFetchResponse)
        except (ConnectionError, LearnShipError):
            pass  # pin TTL covers an unreachable primary
