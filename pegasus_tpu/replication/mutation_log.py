"""Private mutation log (plog): the replica's WAL.

The rDSN mutation log this build re-provides (SURVEY.md §2.4 'Mutation
logs'; config.ini log_private_*): every prepared mutation appends here
BEFORE it is acknowledged, and replay-on-open re-applies committed-but-
unflushed mutations to the engine — the engine itself deliberately has no
WAL (engine/db.py docstring), exactly like the reference runs RocksDB with
WAL disabled because this log is the WAL.

File format: segments log.{start_decree} of framed records
    [u32 len][u32 crc32][payload]
payload = codec-encoded LogMutation. Torn tails (crash mid-append) are
detected by length/crc and truncated at recovery, like mutation_log's
replay cursor. Segments roll at `segment_bytes`; GC drops whole segments
whose decrees are all <= the durable decree.

Why plog-only (no shared log / slog) — a deliberate redesign, not a gap.
The reference historically wrote every mutation TWICE: once to a
node-global shared log (batched, sequential — the commit-latency path)
and once to a per-replica private log (the replay/learn path), because
hundreds of replicas each fsyncing a private WAL would shatter a
spinning disk's sequential bandwidth (config.ini:192-260 tunes both).
Pegasus itself later deprecated the slog (it is absent from modern
apache/incubator-pegasus; log_shared_* knobs were removed) for the same
reasons that apply here, only stronger:

  * this build acknowledges writes from the 2PC quorum over PacificA with
    group commit — one plog append per CONCURRENT BATCH, not per write,
    so the append rate is bounded by batch rounds, not ops;
  * plog appends are buffered sequential writes with fsync optional
    (`fsync=False` default, like log_private flush cadence), so there is
    no per-replica-seek penalty to amortize on modern storage;
  * a single log keyed by decree keeps recovery single-source: replay,
    learner catch-up, duplication catch_up, and mlog_dump all read the
    same stream — the reference needed slog->plog "log split" complexity
    precisely because recovery had two sources of truth.

The one capability the slog bought — cross-replica batched fsync on one
spindle — is irrelevant on flash and under group commit; nothing else in
the recovery story needs it.
"""

import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import List

from ..rpc import codec
from ..runtime.perf_counters import counters
from ..runtime.tracing import REQUEST_TRACER

_FRAME = struct.Struct("<II")


@dataclass
class LogMutation:
    """One decree's mutation batch as it travels prepare->log->apply."""

    decree: int = 0
    ballot: int = 0
    timestamp_us: int = 0
    requests: List[tuple] = field(default_factory=list)  # unused; see codes/bodies

    # codec has no Tuple support; parallel lists keep the frame simple
    codes: List[str] = field(default_factory=list)
    bodies: List[bytes] = field(default_factory=list)


class MutationLog:
    def __init__(self, log_dir: str, segment_bytes: int = 32 << 20,
                 fsync: bool = False):
        self.dir = log_dir
        self.segment_bytes = segment_bytes
        self.fsync = fsync
        self._lock = threading.Lock()
        self._file = None
        self._file_start = None
        self._file_bytes = 0
        self.last_decree = 0
        os.makedirs(log_dir, exist_ok=True)
        self._segments = self._scan_segments()
        if self._segments:
            self.last_decree = self._tail_decree()

    # ----------------------------------------------------------------- write

    def append(self, m: LogMutation) -> None:
        payload = codec.encode(m)
        frame = _FRAME.pack(len(payload), zlib.crc32(payload)) + payload
        t0 = time.perf_counter()
        with REQUEST_TRACER.span("plog.append", decree=m.decree,
                                 bytes=len(frame)), self._lock:
            if self._file is None or self._file_bytes >= self.segment_bytes:
                self._roll_locked(m.decree)
            self._file.write(frame)
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())
            self._file_bytes += len(frame)
            self.last_decree = max(self.last_decree, m.decree)
        counters.rate("plog.append.count").increment()
        counters.rate("plog.append.bytes").increment(len(frame))
        counters.percentile("plog.append.duration_us").set(
            int((time.perf_counter() - t0) * 1e6))

    def _roll_locked(self, start_decree: int) -> None:
        if self._file:
            self._file.close()
        name = f"log.{start_decree}"
        path = os.path.join(self.dir, name)
        self._file = open(path, "ab")
        self._file_start = start_decree
        self._file_bytes = self._file.tell()
        if start_decree not in self._segments:
            self._segments.append(start_decree)
            self._segments.sort()

    # ------------------------------------------------------------------ read

    def replay(self, from_decree: int = 0):
        """Yield LogMutations with decree > from_decree, in append order.
        Stops (and truncates) at the first torn record."""
        with self._lock:
            segments = list(self._segments)
            if self._file:
                self._file.flush()
        for i, start in enumerate(segments):
            # skip segments that end before the replay point
            if i + 1 < len(segments) and segments[i + 1] <= from_decree + 1:
                continue
            path = os.path.join(self.dir, f"log.{start}")
            with open(path, "rb") as f:
                data = f.read()
            off = 0
            while off + _FRAME.size <= len(data):
                length, crc = _FRAME.unpack_from(data, off)
                body = data[off + _FRAME.size : off + _FRAME.size + length]
                if len(body) < length or zlib.crc32(body) != crc:
                    self._truncate_torn(path, off)
                    return
                off += _FRAME.size + length
                m = codec.decode(LogMutation, body)
                if m.decree > from_decree:
                    yield m

    def _truncate_torn(self, path: str, valid_bytes: int) -> None:
        with self._lock:
            if self._file and os.path.join(self.dir, f"log.{self._file_start}") == path:
                self._file.truncate(valid_bytes)
            else:
                with open(path, "r+b") as f:
                    f.truncate(valid_bytes)

    # -------------------------------------------------------------------- gc

    def flush(self) -> None:
        """Flush + fsync the open segment (shell flush_log; reference
        flush_log remote command)."""
        with self._lock:
            if self._file is not None:
                self._file.flush()
                os.fsync(self._file.fileno())

    def gc(self, durable_decree: int) -> int:
        """Drop whole segments strictly older than the segment containing
        durable_decree+1 (reference: log GC after checkpoint)."""
        with self._lock:
            dropped = 0
            while len(self._segments) > 1 and self._segments[1] <= durable_decree + 1:
                start = self._segments.pop(0)
                try:
                    os.unlink(os.path.join(self.dir, f"log.{start}"))
                except OSError:
                    pass
                dropped += 1
            return dropped

    def reset(self) -> None:
        """Wipe everything (learner re-seed from checkpoint)."""
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None
            for start in self._segments:
                try:
                    os.unlink(os.path.join(self.dir, f"log.{start}"))
                except OSError:
                    pass
            self._segments = []
            self.last_decree = 0

    def close(self) -> None:
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None

    # ---------------------------------------------------------------- helpers

    def _scan_segments(self) -> list:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("log.") and name[4:].isdigit():
                out.append(int(name[4:]))
        return sorted(out)

    def _tail_decree(self) -> int:
        last = 0
        for m in self.replay(0):
            last = max(last, m.decree)
        return last
