"""Private mutation log (plog): the replica's WAL.

The rDSN mutation log this build re-provides (SURVEY.md §2.4 'Mutation
logs'; config.ini log_private_*): every prepared mutation appends here
BEFORE it is acknowledged, and replay-on-open re-applies committed-but-
unflushed mutations to the engine — the engine itself deliberately has no
WAL (engine/db.py docstring), exactly like the reference runs RocksDB with
WAL disabled because this log is the WAL.

File format: segments log.{start_decree} of framed records
    [u32 len][u32 crc32][payload]
payload = codec-encoded LogMutation. Torn tails (crash mid-append) are
detected by length/crc and truncated at recovery, like mutation_log's
replay cursor. Segments roll at `segment_bytes`; GC drops whole segments
whose decrees are all <= the durable decree.

Why plog-only (no shared log / slog) — a deliberate redesign, not a gap.
The reference historically wrote every mutation TWICE: once to a
node-global shared log (batched, sequential — the commit-latency path)
and once to a per-replica private log (the replay/learn path), because
hundreds of replicas each fsyncing a private WAL would shatter a
spinning disk's sequential bandwidth (config.ini:192-260 tunes both).
Pegasus itself later deprecated the slog (it is absent from modern
apache/incubator-pegasus; log_shared_* knobs were removed) for the same
reasons that apply here, only stronger:

  * this build acknowledges writes from the 2PC quorum over PacificA with
    group commit — one plog append per CONCURRENT BATCH, not per write,
    so the append rate is bounded by batch rounds, not ops;
  * plog appends are buffered sequential writes with fsync optional
    (`fsync=False` default, like log_private flush cadence), so there is
    no per-replica-seek penalty to amortize on modern storage;
  * a single log keyed by decree keeps recovery single-source: replay,
    learner catch-up, duplication catch_up, and mlog_dump all read the
    same stream — the reference needed slog->plog "log split" complexity
    precisely because recovery had two sources of truth.

The one capability the slog bought — cross-replica batched fsync on one
spindle — is irrelevant on flash and under group commit; nothing else in
the recovery story needs it.

Group commit (the batched fsync the docstring above promises): appends
buffer into a bounded group — the first appender with no active leader
claims everything buffered and lands it with ONE buffered write + ONE
flush (+ one fsync when `fsync=True`); appenders arriving meanwhile form
the next group. `PEGASUS_PLOG_GROUP_N` caps mutations per group (32);
`PEGASUS_PLOG_GROUP_US` (500) bounds how long a leader that claimed a
concurrent group lingers for stragglers — a solo appender never lingers,
so single-writer latency is unchanged. An append returns only after its
group is durable (never ack before durable); a leader wedged between
claim and flush (`plog.group` fail point) degrades unclaimed appends to
the per-append path instead of hanging the partition. Group sizes export
as `plog.append.group_size`, flushes as `plog.append.flush_count`.

Reachability note, to be honest about what runs where: PacificA holds
the replica lock across every append call site, so per-partition the
log sees ONE appender at a time and a group is normally exactly one
append_window entry — the decree window IS the group, and that is where
the batching win comes from. The leader/follower machinery above it is
the general multi-appender contract (chaos tests drive it with raw
threads; a future shared-log caller gets correct grouping for free) and
carries the wedge-degrade path; it adds one cv round-trip, no waiting,
on the solo path.
"""

import os
import struct
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import List

from ..rpc import codec
from ..runtime import lockrank
from ..runtime.fail_points import inject
from ..runtime.perf_counters import counters
from ..runtime.tracing import REQUEST_TRACER

_FRAME = struct.Struct("<II")


class _GroupEntry:
    """One append (or one decree window) waiting for its group to land."""

    __slots__ = ("frames", "decrees", "done", "err")

    def __init__(self, frames, decrees):
        self.frames = frames
        self.decrees = decrees
        self.done = False
        self.err = None


@dataclass
class LogMutation:
    """One decree's mutation batch as it travels prepare->log->apply."""

    decree: int = 0
    ballot: int = 0
    timestamp_us: int = 0
    requests: List[tuple] = field(default_factory=list)  # unused; see codes/bodies

    # codec has no Tuple support; parallel lists keep the frame simple
    codes: List[str] = field(default_factory=list)
    bodies: List[bytes] = field(default_factory=list)


class MutationLog:
    def __init__(self, log_dir: str, segment_bytes: int = 32 << 20,
                 fsync: bool = False, group_n: int = None,
                 group_us: int = None):
        self.dir = log_dir
        self.segment_bytes = segment_bytes
        self.fsync = fsync
        # group commit knobs: a group is capped at `group_n` mutations; a
        # leader that claimed a CONCURRENT group (>= 2 entries) lingers up
        # to `group_us` for stragglers. A solo appender never lingers, so
        # low-QPS latency is unchanged with the knobs at their defaults.
        self.group_n = group_n if group_n is not None else \
            int(os.environ.get("PEGASUS_PLOG_GROUP_N", 32))
        self.group_us = group_us if group_us is not None else \
            int(os.environ.get("PEGASUS_PLOG_GROUP_US", 500))
        # follower stall bound: a group leader wedged between buffer and
        # flush (chaos fail point `plog.group`, or a pathological fsync)
        # must degrade unclaimed appends to the per-append path instead of
        # hanging the partition
        self._stall_s = float(
            os.environ.get("PEGASUS_PLOG_GROUP_STALL_MS", 500)) / 1e3
        self._lock = lockrank.named_lock("plog.file")
        self._gcv = lockrank.named_condition("plog.group")
        # unclaimed _GroupEntry, submit order
        self._gbuf = []            #: guarded_by self._gcv
        # a leader is writing a group
        self._gleader = False      #: guarded_by self._gcv
        # monotonic ts; bypass grouping until
        self._degraded_until = 0.0  #: guarded_by self._gcv
        # monotonic totals (instance-level, so tests can assert the
        # grouping ratio)
        self.append_count = 0      #: guarded_by self._lock
        self.flush_count = 0       #: guarded_by self._lock
        self._file = None          #: guarded_by self._lock
        self._file_start = None    #: guarded_by self._lock
        self._file_bytes = 0       #: guarded_by self._lock
        self.last_decree = 0       #: guarded_by self._lock
        os.makedirs(log_dir, exist_ok=True)
        self._segments = self._scan_segments()
        if self._segments:
            self.last_decree = self._tail_decree()

    # ----------------------------------------------------------------- write

    @staticmethod
    def _frame(m: LogMutation) -> bytes:
        payload = codec.encode(m)
        return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload

    def append(self, m: LogMutation) -> None:
        """Append one mutation; returns once it is DURABLE (its group's
        write+flush(+fsync) completed) — never before."""
        self._submit(_GroupEntry([self._frame(m)], [m.decree]))

    def append_window(self, ms: List[LogMutation]) -> None:
        """Append a contiguous decree window as ONE group member: the
        whole window lands with one buffered write + one flush (+ one
        fsync when armed) — the primary's decree-pipelined prepare path
        and the secondary's windowed on_prepare both land here."""
        if not ms:
            return
        self._submit(_GroupEntry([self._frame(m) for m in ms],
                                 [m.decree for m in ms]))

    def _submit(self, entry: _GroupEntry) -> None:
        t0 = time.perf_counter()
        nbytes = sum(len(f) for f in entry.frames)
        with REQUEST_TRACER.span("plog.append", decree=entry.decrees[-1],
                                 bytes=nbytes, batch=len(entry.frames)):
            if time.monotonic() < self._degraded_until:  #: unguarded_ok racy read of a monotonic degrade hint: worst case one extra grouped (or degraded) append
                # a recent group leader wedged: per-append fallback keeps
                # the partition moving (groups resume after the cooldown)
                self._write_group([entry])
            else:
                self._group_commit(entry)
        if entry.err is not None:
            raise entry.err
        counters.rate("plog.append.count").increment(len(entry.frames))
        counters.rate("plog.append.bytes").increment(nbytes)
        counters.percentile("plog.append.duration_us").set(
            int((time.perf_counter() - t0) * 1e6))

    def _group_commit(self, entry: _GroupEntry) -> None:
        """Leader/follower group commit: the first appender to find no
        active leader claims everything buffered and lands it as one
        group; appenders that arrive while it writes buffer into the NEXT
        group. A follower whose entry is still unclaimed after _stall_s
        steals it back and degrades to the per-append path."""
        with self._gcv:
            self._gbuf.append(entry)
            self._gcv.notify_all()  # wake a lingering leader
        while True:
            fallback = False
            with self._gcv:
                if entry.done:
                    return
                if self._gleader:
                    if self._gcv.wait(self._stall_s):
                        continue
                    if entry not in self._gbuf:
                        continue  # claimed: durability requires waiting
                    # leader wedged and never claimed us: steal our entry
                    # back and degrade to the per-append path for a while
                    self._gbuf.remove(entry)
                    self._degraded_until = time.monotonic() + self._stall_s
                    fallback = True
                else:
                    self._gleader = True
                    batch = self._claim_locked([])
            if fallback:
                counters.rate("plog.group.fallback_count").increment()
                self._write_group([entry])
                return
            # ---- leader, outside the cv: stragglers queue for next group
            try:
                if len(batch) >= 2 and self.group_us > 0:
                    batch = self._linger(batch)
                inject("plog.group")  # chaos seam: between claim and flush
                self._write_group(batch)
            except Exception as e:  # noqa: BLE001 - every member must see it
                err = e if isinstance(e, OSError) else OSError(
                    f"plog group write failed: {e!r}")
                for b in batch:
                    b.err = err
            finally:
                with self._gcv:
                    self._gleader = False
                    for b in batch:
                        b.done = True
                    self._gcv.notify_all()

    def _claim_locked(self, batch: list) -> list:  #: requires self._gcv
        """Move buffered entries into `batch` up to the group_n cap.
        Caller holds self._gcv."""
        total = sum(len(b.frames) for b in batch)
        while self._gbuf and total < self.group_n:
            e = self._gbuf.pop(0)
            batch.append(e)
            total += len(e.frames)
        return batch

    def _linger(self, batch: list) -> list:
        """A leader that already claimed a concurrent group (>= 2 members)
        waits up to group_us for stragglers, growing toward group_n."""
        deadline = time.monotonic() + self.group_us / 1e6
        while sum(len(b.frames) for b in batch) < self.group_n:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            with self._gcv:
                if not self._gbuf:
                    self._gcv.wait(remaining)
                batch = self._claim_locked(batch)
        return batch

    def _write_group(self, batch: list) -> None:
        """Land a claimed group: ONE buffered write + ONE flush (+ one
        fsync when armed) for every frame in every member. The `plog.group`
        fail point fires in _group_commit between claim and flush, OUTSIDE
        the file lock, so a chaos `sleep` wedges only that group — the
        degraded per-append path still reaches the file here."""
        n_frames = sum(len(b.frames) for b in batch)
        blob = b"".join(f for b in batch for f in b.frames)
        first_decree = batch[0].decrees[0]
        with self._lock:
            if self._file is None or self._file_bytes >= self.segment_bytes:
                self._roll_locked(first_decree)
            self._file.write(blob)
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())
            self._file_bytes += len(blob)
            for b in batch:
                self.last_decree = max(self.last_decree, b.decrees[-1])
            self.append_count += n_frames
            self.flush_count += 1
        counters.rate("plog.append.flush_count").increment()
        counters.percentile("plog.append.group_size").set(n_frames)

    def _roll_locked(self, start_decree: int) -> None:  #: requires self._lock
        if self._file:
            self._file.close()
        name = f"log.{start_decree}"
        path = os.path.join(self.dir, name)
        self._file = open(path, "ab")
        self._file_start = start_decree
        self._file_bytes = self._file.tell()
        if start_decree not in self._segments:
            self._segments.append(start_decree)
            self._segments.sort()

    # ------------------------------------------------------------------ read

    def replay(self, from_decree: int = 0):
        """Yield LogMutations with decree > from_decree, in append order.
        Stops (and truncates) at the first torn record."""
        with self._lock:
            segments = list(self._segments)
            if self._file:
                self._file.flush()
        for i, start in enumerate(segments):
            # skip segments that end before the replay point
            if i + 1 < len(segments) and segments[i + 1] <= from_decree + 1:
                continue
            path = os.path.join(self.dir, f"log.{start}")
            with open(path, "rb") as f:
                data = f.read()
            off = 0
            while off + _FRAME.size <= len(data):
                length, crc = _FRAME.unpack_from(data, off)
                body = data[off + _FRAME.size : off + _FRAME.size + length]
                if len(body) < length or zlib.crc32(body) != crc:
                    self._truncate_torn(path, off)
                    return
                off += _FRAME.size + length
                m = codec.decode(LogMutation, body)
                if m.decree > from_decree:
                    yield m

    def _truncate_torn(self, path: str, valid_bytes: int) -> None:
        with self._lock:
            if self._file and os.path.join(self.dir, f"log.{self._file_start}") == path:
                self._file.truncate(valid_bytes)
            else:
                with open(path, "r+b") as f:
                    f.truncate(valid_bytes)

    # -------------------------------------------------------------------- gc

    def flush(self) -> None:
        """Flush + fsync the open segment (shell flush_log; reference
        flush_log remote command)."""
        with self._lock:
            if self._file is not None:
                self._file.flush()
                os.fsync(self._file.fileno())

    def gc(self, durable_decree: int) -> int:
        """Drop whole segments strictly older than the segment containing
        durable_decree+1 (reference: log GC after checkpoint)."""
        with self._lock:
            dropped = 0
            while len(self._segments) > 1 and self._segments[1] <= durable_decree + 1:
                start = self._segments.pop(0)
                try:
                    os.unlink(os.path.join(self.dir, f"log.{start}"))
                except OSError:
                    pass
                dropped += 1
            return dropped

    def reset(self) -> None:
        """Wipe everything (learner re-seed from checkpoint)."""
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None
            for start in self._segments:
                try:
                    os.unlink(os.path.join(self.dir, f"log.{start}"))
                except OSError:
                    pass
            self._segments = []
            self.last_decree = 0

    def close(self) -> None:
        with self._lock:
            if self._file:
                self._file.close()
                self._file = None

    # ---------------------------------------------------------------- helpers

    def _scan_segments(self) -> list:
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("log.") and name[4:].isdigit():
                out.append(int(name[4:]))
        return sorted(out)

    def _tail_decree(self) -> int:
        last = 0
        for m in self.replay(0):
            last = max(last, m.decree)
        return last
