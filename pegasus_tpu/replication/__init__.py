from .group import ReplicaGroup
from .mutation_log import LogMutation, MutationLog
from .replica import (GroupView, LEARNER, PRIMARY, PrepareRejected, Replica,
                      ReplicaError, SECONDARY)

__all__ = [
    "ReplicaGroup", "LogMutation", "MutationLog", "GroupView", "Replica",
    "ReplicaError", "PrepareRejected", "PRIMARY", "SECONDARY", "LEARNER",
]
