"""PacificA replica: prepare/ack/commit 2PC over the mutation log + engine.

The rDSN replication core this build re-provides (SURVEY.md §2.4
'PacificA replication'; knobs config.ini:205-215): one primary serializes
writes per partition; each mutation gets a decree, appends to the private
log, and is sent RPC_PREPARE to every secondary; the primary commits (=
applies to the storage engine via on_batched_write_requests) once
`mutation_2pc_min_replica_count` replicas (incl. itself) hold it in their
logs. Commit points piggyback on later prepares. DECREE PIPELINING:
mutations arriving while a prepare round is in flight coalesce into the
next round — one prepare RPC carries the contiguous decree window
[d1..dk], the plog lands the window as one group append, secondaries
append the window in order and ack the highest contiguous decree, and the
engine applies the committed window in one batched call (per-item
overheads amortize; the protocol itself is untouched). PacificA
invariants kept:

  - prepares apply in decree order; a secondary acks decree d only when its
    log holds every decree <= d (so last_prepared is contiguous coverage);
  - committed(d) => d is in the logs of a quorum => after any crash, the
    live replica with the highest (ballot, last_prepared) holds every
    committed mutation; failover promotes it and commits its whole prepare
    list ("prepared implies eventually committed");
  - a rejoining replica re-seeds as a learner: engine checkpoint copy +
    log tail from the current primary (reference learn flow, SURVEY §3.5).

Engine replay-on-open closes the WAL gap: committed-but-unflushed
mutations are re-applied from the plog before serving.
"""

import os
import threading
import time
from dataclasses import dataclass

from ..engine import EngineOptions
from ..engine.replica_service import WRITE_CODES
from ..engine.server_impl import PegasusServer
from ..rpc import codec
from ..runtime import lockrank
from ..runtime.perf_counters import counters
from ..runtime.tracing import REQUEST_TRACER
from .mutation_log import LogMutation, MutationLog

def _parallel_prepare() -> bool:
    """Concurrent prepare fan-out wins when peer RTT is real network wait
    (multi-host deployments: set PEGASUS_PARALLEL_PREPARE=1). On a
    single-core onebox the 'RTT' is mostly peer CPU under the same GIL and
    the pool dispatch only adds contention — measured 3.4k -> 2.9k ops/s
    YCSB-A at 8 threads — so the default stays sequential."""
    return os.environ.get("PEGASUS_PARALLEL_PREPARE", "0") == "1"


INACTIVE = "INACTIVE"
PRIMARY = "PRIMARY"
SECONDARY = "SECONDARY"
LEARNER = "POTENTIAL_SECONDARY"
ERROR = "ERROR"


class ReplicaError(Exception):
    pass


class PrepareRejected(ReplicaError):
    def __init__(self, reason, last_prepared=0):
        super().__init__(reason)
        self.reason = reason
        self.last_prepared = last_prepared


@dataclass
class GroupView:
    """What the (meta-server stand-in) controller tells members."""

    ballot: int
    primary: str
    secondaries: list


class _WriteSlot:
    __slots__ = ("code", "req", "resp", "err", "done")

    def __init__(self, code, req):
        self.code = code
        self.req = req
        self.resp = None
        self.err = None
        self.done = False


class Replica:
    """One partition replica. `peers` is a callable transport:
    peers(name) -> Replica-like proxy (direct object in-process; an RPC stub
    across processes). Raises ConnectionError for dead nodes."""

    def __init__(self, name: str, path: str, app_id: int = 1, pidx: int = 0,
                 options: EngineOptions = None, peers=None,
                 quorum: int = 2, fsync: bool = False, cluster_id: int = 0):
        self.name = name
        self.path = path
        self.app_id = app_id
        self.pidx = pidx
        self.cluster_id = cluster_id
        self.quorum = quorum
        self.peers = peers or (lambda n: (_ for _ in ()).throw(ConnectionError(n)))
        self._lock = lockrank.named_rlock("replica.lock")
        self.status = INACTIVE  #: guarded_by self._lock
        self.ballot = 0         #: guarded_by self._lock
        self.view = None        #: guarded_by self._lock
        self.server = PegasusServer(os.path.join(path, "data"), app_id=app_id,
                                    pidx=pidx, options=options, server=name,
                                    cluster_id=cluster_id)
        self.plog = MutationLog(os.path.join(path, "plog"), fsync=fsync)
        # decree -> LogMutation (prepared, not applied)
        self._uncommitted = {}   #: guarded_by self._lock
        self._batch_cv = lockrank.named_condition("replica.batch")
        # _WriteSlots awaiting a group commit
        self._batch_pending = []  #: guarded_by self._batch_cv
        self._batch_leader_active = False  #: guarded_by self._batch_cv
        self.commit_hooks = []   # fn(LogMutation) after commit (duplication)
        self.duplicators = {}    # dupid -> MutationDuplicator (stub-managed)
        self.app_name = ""       # set by the stub at open
        self.partition_count = 0
        self.last_committed = self.server.engine.last_committed_decree()  #: guarded_by self._lock
        self.last_prepared = self.last_committed  #: guarded_by self._lock
        self._prep_pool = None
        # replication-lag plane (ISSUE 8): per-partition gauges resolved
        # ONCE (the registry lock is per-lookup and these fire per window)
        pfx = f"replica.{app_id}.{pidx}."
        self._c_inflight = counters.number(pfx + "inflight")
        self._c_backlog = counters.number(pfx + "backlog")
        self._c_committed = counters.number(pfx + "committed_decree")
        self._c_applied = counters.number(pfx + "applied_decree")
        self._c_gap = counters.number(pfx + "secondary_gap_max")
        # compaction-debt plane (ISSUE 10): per-partition gauges the
        # scheduler, doctor and collector read — refreshed per beacon
        # tick from the same engine fold the beacon state carries
        cpfx = f"engine.compact.{app_id}.{pidx}."
        self._c_debt_l0 = counters.number(cpfx + "l0_files")
        self._c_debt_bytes = counters.number(cpfx + "debt_bytes")
        self._c_debt_pending = counters.number(cpfx + "pending_installs")
        self._recover_from_log()

    def _prepare_pool(self):
        if self._prep_pool is None:
            from ..runtime.tasking import tracked_executor

            self._prep_pool = tracked_executor(
                4, thread_name_prefix=f"prep-{self.name}")
        return self._prep_pool

    # ----------------------------------------------------------- recovery

    def _recover_from_log(self):  #: unguarded_ok construction-time: called only from __init__, before the replica is published to any other thread
        """Re-stage every logged mutation after the engine's committed point.
        They stay uncommitted until a view tells us our role (a new primary
        commits them all; a learner discards and re-seeds)."""
        for m in self.plog.replay(0):
            if m.decree > self.last_committed:
                self._uncommitted[m.decree] = m
                self.last_prepared = max(self.last_prepared, m.decree)
            self.ballot = max(self.ballot, m.ballot)

    # --------------------------------------------------------------- views

    def assume_view(self, view: GroupView):
        """Controller-installed configuration (meta server's reconfiguration)."""
        with self._lock:
            self.view = view
            self.ballot = max(self.ballot, view.ballot)
            if view.primary == self.name:
                self.status = PRIMARY
                # PacificA failover rule: commit the entire prepare list
                self._apply_up_to(self.last_prepared)
            elif self.name in view.secondaries:
                self.status = SECONDARY

    # -------------------------------------------------------------- primary

    def client_write(self, code: str, req, now: int = None):
        """The write path: 2PC from the primary (SURVEY §3.2 hot path).

        DECREE PIPELINING: every mutation gets its OWN decree (the
        reference's one-decree-per-mutation shape), but mutations that
        arrive while a prepare round is in flight coalesce into the NEXT
        round — one prepare RPC carries the whole contiguous decree
        window [d1..dk], the plog lands it as one group append, and the
        engine applies the committed window in one batched call. Commit
        points piggyback on later prepares exactly as before."""
        slot = _WriteSlot(code, req)
        with self._batch_cv:
            self._batch_pending.append(slot)
        while True:
            with self._batch_cv:
                if slot.done:
                    break
                if self._batch_leader_active:
                    # handoff is notify-driven (the leader's finally block
                    # notify_all's); the timeout is only a defensive bound,
                    # not a polling cadence (ADVICE r2 weak: 50ms poll)
                    self._batch_cv.wait(0.5)
                    continue
                self._batch_leader_active = True
                batch = self._batch_pending
                self._batch_pending = []
            # this thread leads one window commit (outside the cv so
            # arriving writers can queue for the NEXT window meanwhile)
            try:
                with self._lock:
                    self._commit_window(batch, now=now)
            except Exception as e:  # every waiter must see the failure, not
                for s in batch:     # a silent resp=None "success"
                    if s.err is None and s.resp is None:
                        s.err = e if isinstance(e, ReplicaError) \
                            else ReplicaError(f"group commit failed: {e!r}")
            finally:
                with self._batch_cv:
                    self._batch_leader_active = False
                    for s in batch:
                        s.done = True
                    self._batch_cv.notify_all()
        if slot.err is not None:
            raise slot.err
        return slot.resp

    def _commit_window(self, slots, now=None):  #: requires self._lock
        """One contiguous decree window for `slots` (one decree each);
        caller holds self._lock. Fills each slot's resp/err in place."""
        if self.status != PRIMARY:
            raise ReplicaError(f"{self.name} is not primary")
        d0 = self.last_prepared + 1
        ts = int(time.time() * 1e6)
        ms = [LogMutation(decree=d0 + i, ballot=self.ballot, timestamp_us=ts,
                          codes=[s.code], bodies=[codec.encode(s.req)])
              for i, s in enumerate(slots)]
        dk = ms[-1].decree
        t0 = time.perf_counter()
        with REQUEST_TRACER.span("replica.prepare", decree=dk,
                                 batch=len(ms)):
            self.plog.append_window(ms)
            self.last_prepared = dk
            for m in ms:
                self._uncommitted[m.decree] = m
            secs = list(self.view.secondaries)
            if len(secs) > 1 and _parallel_prepare():
                # prepares fan out concurrently: commit latency is
                # max(peer RTT), not the sum (the reference's parallel
                # RPC_PREPARE sends). Wait for ALL so per-peer prepare
                # order stays monotonic. The trace context is thread-local
                # — each worker adopts it so the peers' prepare spans (and
                # the trace_id on the wire) survive the pool hop.
                ctx = REQUEST_TRACER.current()

                def send(s):
                    with REQUEST_TRACER.adopt(ctx):
                        return self._send_prepare_window(s, ms)

                futs = [self._prepare_pool().submit(send, s) for s in secs]
                peer_lps = [f.result() for f in futs]
            else:
                peer_lps = [self._send_prepare_window(s, ms) for s in secs]
        counters.percentile("replica.prepare_latency_us").set(
            int((time.perf_counter() - t0) * 1e6))
        self._export_gauges()
        # commit point: the highest decree d in the window such that a
        # quorum (incl. us) holds every decree <= d — peers ack their
        # highest CONTIGUOUS prepared decree, so coverage is monotonic
        acks = [lp for lp in peer_lps if lp is not None]
        # worst responding secondary's prepare lag behind this window's
        # tail (dead peers surface via meta liveness, not this gauge)
        self._c_gap.set(max((max(0, dk - lp) for lp in acks), default=0))
        commit_d = d0 - 1
        for d in range(d0, dk + 1):
            if 1 + sum(1 for lp in acks if lp >= d) >= self.quorum:
                commit_d = d
            else:
                break
        if commit_d < d0:
            # cannot commit; leave prepared (a later view change decides)
            raise ReplicaError(
                f"quorum lost: {1 + len(acks)}/{self.quorum} "
                f"for decrees [{d0}..{dk}]")
        t1 = time.perf_counter()
        with REQUEST_TRACER.span("replica.commit", decree=commit_d):
            resps = self._apply_up_to(commit_d, now=now)
        counters.percentile("replica.commit_latency_us").set(
            int((time.perf_counter() - t1) * 1e6))
        self._export_gauges()
        for i, s in enumerate(slots):
            d = d0 + i
            if d <= commit_d:
                rl = resps.get(d)
                s.resp = rl[0] if rl else None
            else:
                s.err = ReplicaError(
                    f"quorum lost: decree {d} prepared but not committed")

    def _export_gauges(self):  #: requires self._lock
        """Per-partition write-path pressure + replication-lag plane:
        slots queued for the next group commit (inflight),
        prepared-but-uncommitted decrees (backlog), and the
        committed/applied decree pair — `committed_decree` is what
        replication knows is committed HERE, `applied_decree` is what the
        engine actually applied; they diverge exactly when a replica is
        behind on APPLY (mid-window engine failure) rather than behind on
        commit, which is the distinction the cluster doctor reports."""
        self._c_inflight.set(len(self._batch_pending))  #: unguarded_ok gauge snapshot of the queue length; the cv would add contention to every write for a stat
        self._c_backlog.set(len(self._uncommitted))
        self._c_committed.set(self.last_committed)
        self._c_applied.set(self.server.engine.last_committed_decree())

    def compact_debt(self) -> dict:
        """Per-partition compaction-debt snapshot (ISSUE 10): one engine
        fold feeding the `engine.compact.<a>.<p>.*` gauges, the beacon
        state the meta snapshot republishes, and db.stats() — the
        scheduler, the doctor and the collector all read the same
        series. Refreshed per beacon tick."""
        debt = self.server.engine.compaction_debt()
        self._c_debt_l0.set(debt["l0_files"])
        self._c_debt_bytes.set(debt["debt_bytes"])
        self._c_debt_pending.set(debt["pending_installs"])
        return debt

    def _send_prepare_window(self, peer_name: str, ms: list):
        """Send one windowed prepare to a peer. Returns the peer's highest
        contiguous prepared decree (its ack), or None for a dead/rejecting
        peer."""
        try:
            peer = self.peers(peer_name)
            try:
                return self._peer_prepare(peer, ms)
            except PrepareRejected as rej:
                if rej.reason == "gap":
                    return self._catch_up_peer(peer, rej.last_prepared, ms)
                return None
        except ConnectionError:
            return None

    def _peer_prepare(self, peer, ms: list):
        """One prepare round against a peer object: windowed when the peer
        supports it, per-mutation for a legacy peer. -> acked decree."""
        if hasattr(peer, "on_prepare_batch"):
            return peer.on_prepare_batch(self.ballot, ms, self.last_committed)  #: unguarded_ok stable during the fan-out: every ballot/commit-point writer needs self._lock, which the window leader holds until all prepare workers return
        for m in ms:
            peer.on_prepare(self.ballot, m, self.last_committed)  #: unguarded_ok stable during the fan-out (see on_prepare_batch above)
        return ms[-1].decree

    def _catch_up_peer(self, peer, peer_prepared: int, ms: list):
        """Stream the missing decrees from our log as chunked windows,
        then retry the current window. -> acked decree or None. A peer
        exposing on_prepare_windows (the RPC proxy) gets the whole backlog
        in ONE coalesced transport send."""
        try:
            backlog = {}
            for lm in self.plog.replay(peer_prepared):
                if lm.decree < ms[0].decree:
                    backlog[lm.decree] = lm  # dedup, newest copy wins
            chunks = [ms]
            ordered = [backlog[d] for d in sorted(backlog)]
            if ordered:
                chunks = [ordered[i:i + 64]
                          for i in range(0, len(ordered), 64)] + [ms]
            if hasattr(peer, "on_prepare_windows"):
                return peer.on_prepare_windows(
                    self.ballot, chunks, self.last_committed)  #: unguarded_ok stable during the fan-out (see on_prepare_batch above)
            lp = None
            for chunk in chunks:
                lp = self._peer_prepare(peer, chunk)
            return lp
        except (PrepareRejected, ConnectionError):
            return None

    # ------------------------------------------------------------ secondary

    def on_prepare_batch(self, ballot: int, ms: list, committed_decree: int):
        """Windowed prepare: stage a contiguous decree window with ONE
        plog group append and ack the highest contiguous prepared decree.
        The per-decree invariants are exactly on_prepare's — ack(d) only
        once the log holds every decree <= d. An EMPTY window is a pure
        commit-point broadcast (broadcast_commit_point): nothing stages,
        but staged decrees covered by `committed_decree` apply — how an
        idle partition's secondaries learn the last window committed."""
        with REQUEST_TRACER.span("replica.on_prepare",
                                 decree=ms[-1].decree if ms
                                 else committed_decree,
                                 batch=len(ms)), self._lock:
            if ballot < self.ballot:
                raise PrepareRejected("stale_ballot", self.last_prepared)
            self.ballot = ballot
            fresh, gap = [], False
            for m in ms:
                if m.decree <= self.last_committed:
                    continue  # already committed: drop (see on_prepare)
                if m.decree <= self.last_prepared:
                    # duplicate (catch-up overlap): keep newest copy staged
                    self._uncommitted.setdefault(m.decree, m)
                elif m.decree == self.last_prepared + len(fresh) + 1:
                    fresh.append(m)
                elif m.decree <= self.last_prepared + len(fresh):
                    pass  # duplicates a decree already in this window
                else:
                    gap = True
                    break
            if fresh:
                # durability before ack: the window is in the log (one
                # group flush) before last_prepared moves
                self.plog.append_window(fresh)
                for m in fresh:
                    self._uncommitted[m.decree] = m
                self.last_prepared = fresh[-1].decree
            self._apply_up_to(min(committed_decree, self.last_prepared))
            self._export_gauges()
            if gap:
                raise PrepareRejected("gap", self.last_prepared)
            return self.last_prepared

    def broadcast_commit_point(self) -> int:
        """Push the current commit point to every secondary as an EMPTY
        prepare window, so decrees they hold prepared apply NOW instead
        of waiting for the next write's piggyback. trigger_audit needs
        this: on an idle partition the audit decree would otherwise sit
        staged on secondaries indefinitely and the audit could never
        conclude. -> number of peers that acked."""
        with self._lock:
            if self.status != PRIMARY or self.view is None:
                return 0
            secs = list(self.view.secondaries)
            ballot, committed = self.ballot, self.last_committed
        n = 0
        for s in secs:
            try:
                peer = self.peers(s)
                if hasattr(peer, "on_prepare_batch"):
                    peer.on_prepare_batch(ballot, [], committed)
                    n += 1
            except (PrepareRejected, ConnectionError):
                continue
        return n

    def on_prepare(self, ballot: int, m: LogMutation, committed_decree: int):
        with REQUEST_TRACER.span("replica.on_prepare", decree=m.decree), \
                self._lock:
            if ballot < self.ballot:
                raise PrepareRejected("stale_ballot", self.last_prepared)
            self.ballot = ballot
            if m.decree <= self.last_committed:
                # already committed: drop — staging it would leak, since
                # _apply_up_to only ever pops decrees > last_committed
                # (ADVICE r2 low)
                pass
            elif m.decree <= self.last_prepared:
                # duplicate (catch-up overlap): keep newest copy staged
                self._uncommitted.setdefault(m.decree, m)
            elif m.decree == self.last_prepared + 1:
                self.plog.append(m)
                self.last_prepared = m.decree
                self._uncommitted[m.decree] = m
            else:
                raise PrepareRejected("gap", self.last_prepared)
            self._apply_up_to(min(committed_decree, self.last_prepared))

    # ---------------------------------------------------------------- apply

    def _apply_up_to(self, decree: int, now: int = None):  #: requires self._lock
        """Commit staged mutations in order through the storage engine —
        the whole contiguous window in ONE batched engine call
        (on_batched_write_window: consecutive batchable decrees share one
        WriteBatch and one engine lock acquisition). Returns
        {decree: response list} for every decree applied."""
        if self.last_committed >= decree:
            return {}
        window, ms = [], []
        for d in range(self.last_committed + 1, decree + 1):
            m = self._uncommitted.pop(d, None)
            if m is None:
                raise ReplicaError(f"{self.name}: commit gap at decree {d}")
            reqs = []
            for code, body in zip(m.codes, m.bodies):
                req_cls, _ = WRITE_CODES[code]
                reqs.append((code, codec.decode(req_cls, body)))
            window.append((d, m.timestamp_us, reqs))
            ms.append(m)
        try:
            resps = self.server.on_batched_write_window(window, now=now)
        except Exception:
            # a mid-window engine failure (fail points) leaves the engine
            # at its own committed point: re-stage what was not applied so
            # a later view change or retry can still commit it, and fire
            # the commit hooks for what WAS applied — a duplication
            # shipper advances past this window on the next commit, so a
            # decree skipped here would never ship
            applied = self.server.engine.last_committed_decree()
            for m in ms:
                if m.decree > applied:
                    self._uncommitted[m.decree] = m
                else:
                    for hook in self.commit_hooks:
                        hook(m)
            self.last_committed = max(self.last_committed, applied)
            raise
        self.last_committed = decree
        for m in ms:
            for hook in self.commit_hooks:
                hook(m)
        return resps

    # --------------------------------------------------------------- learner

    def learn_from(self, primary):
        """Re-seed from the primary: checkpoint copy + log tail
        (reference learn flow: get_checkpoint -> storage_apply_checkpoint ->
        replay private log, SURVEY §3.5). `primary` is anything exposing
        fetch_learn_state() — a local Replica or an RPC peer proxy (the
        NFS-like learn file copy of config.ini:64-73)."""
        from ..runtime import events

        learning = counters.number(
            f"replica.{self.app_id}.{self.pidx}.learning")
        learning.set(1)
        events.emit("learn.start", gpid=f"{self.app_id}.{self.pidx}")
        t0 = time.monotonic()
        ok = False
        try:
            self._learn_from(primary)
            ok = True
        finally:
            learning.set(0)
            events.emit("learn.finish", severity="info" if ok else "error",
                        gpid=f"{self.app_id}.{self.pidx}", ok=ok,
                        dur_s=round(time.monotonic() - t0, 3),
                        committed=self.last_committed)  #: unguarded_ok post-learn snapshot for the event record; _learn_from already released the lock and the value only moves forward
            self._export_gauges()

    def _learn_from(self, primary):
        with self._lock:
            self.status = LEARNER
            self._uncommitted.clear()
            state = primary.fetch_learn_state()
            self.server.close()
            ckpt_dir = os.path.join(self.path, "learn_ckpt")
            if os.path.exists(ckpt_dir):
                import shutil

                shutil.rmtree(ckpt_dir)
            os.makedirs(ckpt_dir)
            for fname, blob in state["files"]:
                with open(os.path.join(ckpt_dir, fname), "wb") as f:
                    f.write(blob)
            from ..engine.db import LsmEngine

            engine = LsmEngine.apply_checkpoint(
                ckpt_dir, os.path.join(self.path, "data"),
                self.server.engine.opts)
            self.server = PegasusServer.__new__(PegasusServer)
            self.server.__init__(os.path.join(self.path, "data"),
                                 app_id=self.app_id, pidx=self.pidx,
                                 options=engine.opts, server=self.name,
                                 cluster_id=self.cluster_id)
            self.plog.reset()
            self.last_committed = self.server.engine.last_committed_decree()
            self.last_prepared = self.last_committed
            # pull the tail beyond the checkpoint
            for m in state["tail"]:
                if m.decree <= self.last_prepared:
                    continue
                self.plog.append(m)
                self.last_prepared = m.decree
                self._uncommitted[m.decree] = m
            self._apply_up_to(min(state["last_committed"], self.last_prepared))
            self.ballot = max(self.ballot, state["ballot"])
            self.status = SECONDARY

    def fetch_learn_state(self) -> dict:
        """Primary side of learn: checkpoint files + log tail + watermarks."""
        with self._lock:
            self.server.engine.sync_checkpoint()
            ckpt = self.server.engine.get_checkpoint_dir()
            files = []
            for fname in sorted(os.listdir(ckpt)):
                p = os.path.join(ckpt, fname)
                if os.path.isfile(p):
                    with open(p, "rb") as f:
                        files.append((fname, f.read()))
            tail = list(self.plog.replay(self.server.engine.last_durable_decree()))
            return {"files": files, "tail": tail,
                    "last_committed": self.last_committed, "ballot": self.ballot}

    # ------------------------------------------------------------- plumbing

    def gc_log(self, flush: bool = False):
        """Drop log segments the durable SSTs cover. flush=True forces the
        memtable down first (tests); the maintenance timer must NOT — a
        periodic forced flush would churn tiny L0 files on idle tables.
        Active duplications hold the log at their confirmed decree: a
        restarted/promoted shipper must be able to catch_up() from plog
        (the reference keeps plog for dup the same way)."""
        if flush:
            self.server.engine.flush()
        floor = self.server.engine.last_durable_decree()
        # Per dup entry the holdback decree is the freshest confirmed point
        # we know: our own shipper's progress when we run one (primary),
        # else the meta-confirmed decree the env carries (secondaries hold
        # the log too — on promotion the new primary catches up from ITS
        # plog, so gc'ing past that floor would open a duplication gap; the
        # meta re-pushes refreshed entries periodically so this floor
        # advances on stable clusters instead of pinning the log at 0).
        entries = {e["dupid"]: e for e in self._dup_env_entries()
                   if e.get("status") in ("init", "start", "pause")}
        dups = dict(self.duplicators)
        for dupid, e in entries.items():
            conf = int(e.get("confirmed", {}).get(str(self.pidx), 0))
            d = dups.get(dupid)
            floor = min(floor, max(conf, d.last_shipped_decree) if d else conf)
        for dupid, d in dups.items():
            if dupid not in entries:  # shipper ahead of the env snapshot
                floor = min(floor, d.last_shipped_decree)
        self.plog.gc(floor)

    def _dup_env_entries(self) -> list:
        import json

        from ..base import consts

        try:
            return json.loads(
                self.server.app_envs.get(consts.ENV_DUPLICATION_KEY, "[]"))
        except ValueError:
            return []

    def close(self):
        for dupid, d in self.duplicators.items():
            d.stop()
            counters.remove(f"dup.lag.{self.app_id}.{self.pidx}.{dupid}")
        self.duplicators.clear()
        # unregister this partition's lag gauges: a closed (rebalanced
        # away) replica's frozen values must not keep feeding the
        # collector's cluster worst-offender series
        for name in ("inflight", "backlog", "committed_decree",
                     "applied_decree", "secondary_gap_max", "learning"):
            counters.remove(f"replica.{self.app_id}.{self.pidx}.{name}")
        for name in ("l0_files", "debt_bytes", "pending_installs"):
            counters.remove(
                f"engine.compact.{self.app_id}.{self.pidx}.{name}")
        if self._prep_pool is not None:
            self._prep_pool.shutdown(wait=False)
            self._prep_pool = None
        self.plog.close()
        self.server.close()
