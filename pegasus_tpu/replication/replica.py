"""PacificA replica: prepare/ack/commit 2PC over the mutation log + engine.

The rDSN replication core this build re-provides (SURVEY.md §2.4
'PacificA replication'; knobs config.ini:205-215): one primary serializes
writes per partition; each mutation gets a decree, appends to the private
log, and is sent RPC_PREPARE to every secondary; the primary commits (=
applies to the storage engine via on_batched_write_requests) once
`mutation_2pc_min_replica_count` replicas (incl. itself) hold it in their
logs. Commit points piggyback on later prepares. DECREE PIPELINING:
mutations arriving while a prepare round is in flight coalesce into the
next round — one prepare RPC carries the contiguous decree window
[d1..dk], the plog lands the window as one group append, secondaries
append the window in order and ack the highest contiguous decree, and the
engine applies the committed window in one batched call (per-item
overheads amortize; the protocol itself is untouched). PacificA
invariants kept:

  - prepares apply in decree order; a secondary acks decree d only when its
    log holds every decree <= d (so last_prepared is contiguous coverage);
  - committed(d) => d is in the logs of a quorum => after any crash, the
    live replica with the highest (ballot, last_prepared) holds every
    committed mutation; failover promotes it and commits its whole prepare
    list ("prepared implies eventually committed");
  - a rejoining replica re-seeds as a learner: engine checkpoint copy +
    log tail from the current primary (reference learn flow, SURVEY §3.5).

Engine replay-on-open closes the WAL gap: committed-but-unflushed
mutations are re-applied from the plog before serving.
"""

import os
import threading
import time
from dataclasses import dataclass

from ..engine import EngineOptions
from ..engine.replica_service import WRITE_CODES
from ..engine.server_impl import PegasusServer
from ..rpc import codec
from ..runtime import lockrank
from ..runtime.perf_counters import counters
from ..runtime.tracing import REQUEST_TRACER
from .mutation_log import LogMutation, MutationLog

def _parallel_prepare() -> bool:
    """Concurrent prepare fan-out wins when peer RTT is real network wait
    (multi-host deployments: set PEGASUS_PARALLEL_PREPARE=1). On a
    single-core onebox the 'RTT' is mostly peer CPU under the same GIL and
    the pool dispatch only adds contention — measured 3.4k -> 2.9k ops/s
    YCSB-A at 8 threads — so the default stays sequential."""
    return os.environ.get("PEGASUS_PARALLEL_PREPARE", "0") == "1"


INACTIVE = "INACTIVE"
PRIMARY = "PRIMARY"
SECONDARY = "SECONDARY"
LEARNER = "POTENTIAL_SECONDARY"
ERROR = "ERROR"


class ReplicaError(Exception):
    pass


class PrepareRejected(ReplicaError):
    def __init__(self, reason, last_prepared=0):
        super().__init__(reason)
        self.reason = reason
        self.last_prepared = last_prepared


@dataclass
class GroupView:
    """What the (meta-server stand-in) controller tells members."""

    ballot: int
    primary: str
    secondaries: list


class _WriteSlot:
    __slots__ = ("code", "req", "resp", "err", "done")

    def __init__(self, code, req):
        self.code = code
        self.req = req
        self.resp = None
        self.err = None
        self.done = False


class Replica:
    """One partition replica. `peers` is a callable transport:
    peers(name) -> Replica-like proxy (direct object in-process; an RPC stub
    across processes). Raises ConnectionError for dead nodes."""

    def __init__(self, name: str, path: str, app_id: int = 1, pidx: int = 0,
                 options: EngineOptions = None, peers=None,
                 quorum: int = 2, fsync: bool = False, cluster_id: int = 0):
        self.name = name
        self.path = path
        self.app_id = app_id
        self.pidx = pidx
        self.cluster_id = cluster_id
        self.quorum = quorum
        self.peers = peers or (lambda n: (_ for _ in ()).throw(ConnectionError(n)))
        self._lock = lockrank.named_rlock("replica.lock")
        self.status = INACTIVE  #: guarded_by self._lock
        self.ballot = 0         #: guarded_by self._lock
        self.view = None        #: guarded_by self._lock
        # a streamed learn is staging blocks with self._lock RELEASED
        # (ISSUE 13): prepares arriving meanwhile are rejected instead of
        # interleaving with the staged state (the primary treats the
        # rejection as a missing ack; the post-swap gap path catches up)
        self._learning = False  #: guarded_by self._lock
        # primary-side learn pins (ISSUE 13): learn_id -> pin record.
        # While pinned, plog GC floors at the pinned checkpoint decree
        # (the tail fetch must stay replayable) and the engine holds the
        # pinned checkpoint out of its own GC. Leaf lock (never nests
        # another lock under it).
        self._learn_lock = lockrank.named_lock("replica.learn_pins")
        self._learn_pins = {}   #: guarded_by self._learn_lock
        self._learn_next_id = 0  #: guarded_by self._learn_lock
        # learner-side serialization: the transfer runs with self._lock
        # released, so without this a meta retry (its open RPC timing out
        # while the first learn still streams) would start a SECOND learn
        # staging into the same learn_ckpt/ dir mid-flight
        self._learn_serial = lockrank.named_lock("replica.learn_serial")
        self.server = PegasusServer(os.path.join(path, "data"), app_id=app_id,
                                    pidx=pidx, options=options, server=name,
                                    cluster_id=cluster_id)
        # on-disk corruption callout (ISSUE 17): the stub points this at
        # its quarantine machinery; kept on the Replica (not just the
        # engine) because a learn replaces the engine wholesale and the
        # fresh one must keep reporting
        self.corruption_hook = None
        self.plog = MutationLog(os.path.join(path, "plog"), fsync=fsync)
        # decree -> LogMutation (prepared, not applied)
        self._uncommitted = {}   #: guarded_by self._lock
        self._batch_cv = lockrank.named_condition("replica.batch")
        # _WriteSlots awaiting a group commit
        self._batch_pending = []  #: guarded_by self._batch_cv
        self._batch_leader_active = False  #: guarded_by self._batch_cv
        self.commit_hooks = []   # fn(LogMutation) after commit (duplication)
        self.duplicators = {}    # dupid -> MutationDuplicator (stub-managed)
        self.app_name = ""       # set by the stub at open
        self.partition_count = 0
        self.last_committed = self.server.engine.last_committed_decree()  #: guarded_by self._lock
        self.last_prepared = self.last_committed  #: guarded_by self._lock
        self._prep_pool = None
        # replication-lag plane (ISSUE 8): per-partition gauges resolved
        # ONCE (the registry lock is per-lookup and these fire per window)
        pfx = f"replica.{app_id}.{pidx}."
        self._c_inflight = counters.number(pfx + "inflight")
        self._c_backlog = counters.number(pfx + "backlog")
        self._c_committed = counters.number(pfx + "committed_decree")
        self._c_applied = counters.number(pfx + "applied_decree")
        self._c_gap = counters.number(pfx + "secondary_gap_max")
        # compaction-debt plane (ISSUE 10): per-partition gauges the
        # scheduler, doctor and collector read — refreshed per beacon
        # tick from the same engine fold the beacon state carries
        cpfx = f"engine.compact.{app_id}.{pidx}."
        self._c_debt_l0 = counters.number(cpfx + "l0_files")
        self._c_debt_bytes = counters.number(cpfx + "debt_bytes")
        self._c_debt_pending = counters.number(cpfx + "pending_installs")
        self._recover_from_log()

    def _prepare_pool(self):
        if self._prep_pool is None:
            from ..runtime.tasking import tracked_executor

            self._prep_pool = tracked_executor(
                4, thread_name_prefix=f"prep-{self.name}")
        return self._prep_pool

    def set_corruption_hook(self, fn) -> None:
        """Install the stub's read-path corruption callout on this replica
        AND its current engine (future engines — learn swaps — inherit it
        from self.corruption_hook in _swap_learned_state)."""
        self.corruption_hook = fn
        self.server.engine.corruption_hook = fn

    # ----------------------------------------------------------- recovery

    def _recover_from_log(self):  #: unguarded_ok construction-time: called only from __init__, before the replica is published to any other thread
        """Re-stage every logged mutation after the engine's committed point.
        They stay uncommitted until a view tells us our role (a new primary
        commits them all; a learner discards and re-seeds)."""
        for m in self.plog.replay(0):
            if m.decree > self.last_committed:
                self._uncommitted[m.decree] = m
                self.last_prepared = max(self.last_prepared, m.decree)
            self.ballot = max(self.ballot, m.ballot)

    # --------------------------------------------------------------- views

    def assume_view(self, view: GroupView):
        """Controller-installed configuration (meta server's reconfiguration)."""
        with self._lock:
            self.view = view
            self.ballot = max(self.ballot, view.ballot)
            if view.primary == self.name:
                self.status = PRIMARY
                # PacificA failover rule: commit the entire prepare list
                self._apply_up_to(self.last_prepared)
            elif self.name in view.secondaries:
                self.status = SECONDARY

    # -------------------------------------------------------------- primary

    def client_write(self, code: str, req, now: int = None):
        """The write path: 2PC from the primary (SURVEY §3.2 hot path).

        DECREE PIPELINING: every mutation gets its OWN decree (the
        reference's one-decree-per-mutation shape), but mutations that
        arrive while a prepare round is in flight coalesce into the NEXT
        round — one prepare RPC carries the whole contiguous decree
        window [d1..dk], the plog lands it as one group append, and the
        engine applies the committed window in one batched call. Commit
        points piggyback on later prepares exactly as before."""
        slot = _WriteSlot(code, req)
        with self._batch_cv:
            self._batch_pending.append(slot)
        while True:
            with self._batch_cv:
                if slot.done:
                    break
                if self._batch_leader_active:
                    # handoff is notify-driven (the leader's finally block
                    # notify_all's); the timeout is only a defensive bound,
                    # not a polling cadence (ADVICE r2 weak: 50ms poll)
                    self._batch_cv.wait(0.5)
                    continue
                self._batch_leader_active = True
                batch = self._batch_pending
                self._batch_pending = []
            # this thread leads one window commit (outside the cv so
            # arriving writers can queue for the NEXT window meanwhile)
            try:
                with self._lock:
                    self._commit_window(batch, now=now)
            except Exception as e:  # every waiter must see the failure, not
                for s in batch:     # a silent resp=None "success"
                    if s.err is None and s.resp is None:
                        s.err = e if isinstance(e, ReplicaError) \
                            else ReplicaError(f"group commit failed: {e!r}")
            finally:
                with self._batch_cv:
                    self._batch_leader_active = False
                    for s in batch:
                        s.done = True
                    self._batch_cv.notify_all()
        if slot.err is not None:
            raise slot.err
        return slot.resp

    def _commit_window(self, slots, now=None):  #: requires self._lock
        """One contiguous decree window for `slots` (one decree each);
        caller holds self._lock. Fills each slot's resp/err in place."""
        if self.status != PRIMARY:
            raise ReplicaError(f"{self.name} is not primary")
        d0 = self.last_prepared + 1
        ts = int(time.time() * 1e6)
        ms = [LogMutation(decree=d0 + i, ballot=self.ballot, timestamp_us=ts,
                          codes=[s.code], bodies=[codec.encode(s.req)])
              for i, s in enumerate(slots)]
        dk = ms[-1].decree
        t0 = time.perf_counter()
        with REQUEST_TRACER.span("replica.prepare", decree=dk,
                                 batch=len(ms)):
            self.plog.append_window(ms)
            self.last_prepared = dk
            for m in ms:
                self._uncommitted[m.decree] = m
            secs = list(self.view.secondaries)
            if len(secs) > 1 and _parallel_prepare():
                # prepares fan out concurrently: commit latency is
                # max(peer RTT), not the sum (the reference's parallel
                # RPC_PREPARE sends). Wait for ALL so per-peer prepare
                # order stays monotonic. The trace context is thread-local
                # — each worker adopts it so the peers' prepare spans (and
                # the trace_id on the wire) survive the pool hop.
                ctx = REQUEST_TRACER.current()

                def send(s):
                    with REQUEST_TRACER.adopt(ctx):
                        return self._send_prepare_window(s, ms)

                futs = [self._prepare_pool().submit(send, s) for s in secs]
                peer_lps = [f.result() for f in futs]
            else:
                peer_lps = [self._send_prepare_window(s, ms) for s in secs]
        counters.percentile("replica.prepare_latency_us").set(
            int((time.perf_counter() - t0) * 1e6))
        self._export_gauges()
        # commit point: the highest decree d in the window such that a
        # quorum (incl. us) holds every decree <= d — peers ack their
        # highest CONTIGUOUS prepared decree, so coverage is monotonic
        acks = [lp for lp in peer_lps if lp is not None]
        # worst responding secondary's prepare lag behind this window's
        # tail (dead peers surface via meta liveness, not this gauge)
        self._c_gap.set(max((max(0, dk - lp) for lp in acks), default=0))
        commit_d = d0 - 1
        for d in range(d0, dk + 1):
            if 1 + sum(1 for lp in acks if lp >= d) >= self.quorum:
                commit_d = d
            else:
                break
        if commit_d < d0:
            # cannot commit; leave prepared (a later view change decides)
            raise ReplicaError(
                f"quorum lost: {1 + len(acks)}/{self.quorum} "
                f"for decrees [{d0}..{dk}]")
        t1 = time.perf_counter()
        with REQUEST_TRACER.span("replica.commit", decree=commit_d):
            resps = self._apply_up_to(commit_d, now=now)
        counters.percentile("replica.commit_latency_us").set(
            int((time.perf_counter() - t1) * 1e6))
        self._export_gauges()
        for i, s in enumerate(slots):
            d = d0 + i
            if d <= commit_d:
                rl = resps.get(d)
                s.resp = rl[0] if rl else None
            else:
                s.err = ReplicaError(
                    f"quorum lost: decree {d} prepared but not committed")

    def _export_gauges(self):  #: requires self._lock
        """Per-partition write-path pressure + replication-lag plane:
        slots queued for the next group commit (inflight),
        prepared-but-uncommitted decrees (backlog), and the
        committed/applied decree pair — `committed_decree` is what
        replication knows is committed HERE, `applied_decree` is what the
        engine actually applied; they diverge exactly when a replica is
        behind on APPLY (mid-window engine failure) rather than behind on
        commit, which is the distinction the cluster doctor reports."""
        self._c_inflight.set(len(self._batch_pending))  #: unguarded_ok gauge snapshot of the queue length; the cv would add contention to every write for a stat
        self._c_backlog.set(len(self._uncommitted))
        self._c_committed.set(self.last_committed)
        self._c_applied.set(self.server.engine.last_committed_decree())

    def compact_debt(self) -> dict:
        """Per-partition compaction-debt snapshot (ISSUE 10): one engine
        fold feeding the `engine.compact.<a>.<p>.*` gauges, the beacon
        state the meta snapshot republishes, and db.stats() — the
        scheduler, the doctor and the collector all read the same
        series. Refreshed per beacon tick."""
        debt = self.server.engine.compaction_debt()
        self._c_debt_l0.set(debt["l0_files"])
        self._c_debt_bytes.set(debt["debt_bytes"])
        self._c_debt_pending.set(debt["pending_installs"])
        return debt

    def _send_prepare_window(self, peer_name: str, ms: list):
        """Send one windowed prepare to a peer. Returns the peer's highest
        contiguous prepared decree (its ack), or None for a dead/rejecting
        peer."""
        try:
            peer = self.peers(peer_name)
            try:
                return self._peer_prepare(peer, ms)
            except PrepareRejected as rej:
                if rej.reason == "gap":
                    return self._catch_up_peer(peer, rej.last_prepared, ms)
                return None
        except ConnectionError:
            return None

    def _peer_prepare(self, peer, ms: list):
        """One prepare round against a peer object: windowed when the peer
        supports it, per-mutation for a legacy peer. -> acked decree."""
        if hasattr(peer, "on_prepare_batch"):
            return peer.on_prepare_batch(self.ballot, ms, self.last_committed)  #: unguarded_ok stable during the fan-out: every ballot/commit-point writer needs self._lock, which the window leader holds until all prepare workers return
        for m in ms:
            peer.on_prepare(self.ballot, m, self.last_committed)  #: unguarded_ok stable during the fan-out (see on_prepare_batch above)
        return ms[-1].decree

    def _catch_up_peer(self, peer, peer_prepared: int, ms: list):
        """Stream the missing decrees from our log as chunked windows,
        then retry the current window. -> acked decree or None. A peer
        exposing on_prepare_windows (the RPC proxy) gets the whole backlog
        in ONE coalesced transport send."""
        try:
            backlog = {}
            for lm in self.plog.replay(peer_prepared):
                if lm.decree < ms[0].decree:
                    backlog[lm.decree] = lm  # dedup, newest copy wins
            chunks = [ms]
            ordered = [backlog[d] for d in sorted(backlog)]
            if ordered:
                chunks = [ordered[i:i + 64]
                          for i in range(0, len(ordered), 64)] + [ms]
            if hasattr(peer, "on_prepare_windows"):
                return peer.on_prepare_windows(
                    self.ballot, chunks, self.last_committed)  #: unguarded_ok stable during the fan-out (see on_prepare_batch above)
            lp = None
            for chunk in chunks:
                lp = self._peer_prepare(peer, chunk)
            return lp
        except (PrepareRejected, ConnectionError):
            return None

    # ------------------------------------------------------------ secondary

    def on_prepare_batch(self, ballot: int, ms: list, committed_decree: int):
        """Windowed prepare: stage a contiguous decree window with ONE
        plog group append and ack the highest contiguous prepared decree.
        The per-decree invariants are exactly on_prepare's — ack(d) only
        once the log holds every decree <= d. An EMPTY window is a pure
        commit-point broadcast (broadcast_commit_point): nothing stages,
        but staged decrees covered by `committed_decree` apply — how an
        idle partition's secondaries learn the last window committed."""
        with REQUEST_TRACER.span("replica.on_prepare",
                                 decree=ms[-1].decree if ms
                                 else committed_decree,
                                 batch=len(ms)), self._lock:
            if self._learning:
                # mid-learn: the staged state is about to replace this
                # replica wholesale — interleaving prepares would be
                # wiped (or worse, survive the swap). The primary treats
                # this as a missing ack; post-swap the gap path catches
                # up from the primary's log.
                raise PrepareRejected("learning", self.last_prepared)
            if ballot < self.ballot:
                raise PrepareRejected("stale_ballot", self.last_prepared)
            self.ballot = ballot
            fresh, gap = [], False
            for m in ms:
                if m.decree <= self.last_committed:
                    continue  # already committed: drop (see on_prepare)
                if m.decree <= self.last_prepared:
                    # duplicate (catch-up overlap): keep newest copy staged
                    self._uncommitted.setdefault(m.decree, m)
                elif m.decree == self.last_prepared + len(fresh) + 1:
                    fresh.append(m)
                elif m.decree <= self.last_prepared + len(fresh):
                    pass  # duplicates a decree already in this window
                else:
                    gap = True
                    break
            if fresh:
                # durability before ack: the window is in the log (one
                # group flush) before last_prepared moves
                self.plog.append_window(fresh)
                for m in fresh:
                    self._uncommitted[m.decree] = m
                self.last_prepared = fresh[-1].decree
            self._apply_up_to(min(committed_decree, self.last_prepared))
            self._export_gauges()
            if gap:
                raise PrepareRejected("gap", self.last_prepared)
            return self.last_prepared

    def broadcast_commit_point(self) -> int:
        """Push the current commit point to every secondary as an EMPTY
        prepare window, so decrees they hold prepared apply NOW instead
        of waiting for the next write's piggyback. trigger_audit needs
        this: on an idle partition the audit decree would otherwise sit
        staged on secondaries indefinitely and the audit could never
        conclude. -> number of peers that acked."""
        with self._lock:
            if self.status != PRIMARY or self.view is None:
                return 0
            secs = list(self.view.secondaries)
            ballot, committed = self.ballot, self.last_committed
        n = 0
        for s in secs:
            try:
                peer = self.peers(s)
                if hasattr(peer, "on_prepare_batch"):
                    peer.on_prepare_batch(ballot, [], committed)
                    n += 1
            except (PrepareRejected, ConnectionError):
                continue
        return n

    def on_prepare(self, ballot: int, m: LogMutation, committed_decree: int):
        with REQUEST_TRACER.span("replica.on_prepare", decree=m.decree), \
                self._lock:
            if self._learning:
                raise PrepareRejected("learning", self.last_prepared)
            if ballot < self.ballot:
                raise PrepareRejected("stale_ballot", self.last_prepared)
            self.ballot = ballot
            if m.decree <= self.last_committed:
                # already committed: drop — staging it would leak, since
                # _apply_up_to only ever pops decrees > last_committed
                # (ADVICE r2 low)
                pass
            elif m.decree <= self.last_prepared:
                # duplicate (catch-up overlap): keep newest copy staged
                self._uncommitted.setdefault(m.decree, m)
            elif m.decree == self.last_prepared + 1:
                self.plog.append(m)
                self.last_prepared = m.decree
                self._uncommitted[m.decree] = m
            else:
                raise PrepareRejected("gap", self.last_prepared)
            self._apply_up_to(min(committed_decree, self.last_prepared))

    # ---------------------------------------------------------------- apply

    def _apply_up_to(self, decree: int, now: int = None):  #: requires self._lock
        """Commit staged mutations in order through the storage engine —
        the whole contiguous window in ONE batched engine call
        (on_batched_write_window: consecutive batchable decrees share one
        WriteBatch and one engine lock acquisition). Returns
        {decree: response list} for every decree applied."""
        if self.last_committed >= decree:
            return {}
        window, ms = [], []
        for d in range(self.last_committed + 1, decree + 1):
            m = self._uncommitted.pop(d, None)
            if m is None:
                raise ReplicaError(f"{self.name}: commit gap at decree {d}")
            reqs = []
            for code, body in zip(m.codes, m.bodies):
                req_cls, _ = WRITE_CODES[code]
                reqs.append((code, codec.decode(req_cls, body)))
            window.append((d, m.timestamp_us, reqs))
            ms.append(m)
        try:
            resps = self.server.on_batched_write_window(window, now=now)
        except Exception:
            # a mid-window engine failure (fail points) leaves the engine
            # at its own committed point: re-stage what was not applied so
            # a later view change or retry can still commit it, and fire
            # the commit hooks for what WAS applied — a duplication
            # shipper advances past this window on the next commit, so a
            # decree skipped here would never ship
            applied = self.server.engine.last_committed_decree()
            for m in ms:
                if m.decree > applied:
                    self._uncommitted[m.decree] = m
                else:
                    for hook in self.commit_hooks:
                        hook(m)
            self.last_committed = max(self.last_committed, applied)
            raise
        self.last_committed = decree
        for m in ms:
            for hook in self.commit_hooks:
                hook(m)
        return resps

    # --------------------------------------------------------------- learner

    def learn_from(self, primary):
        """Re-seed from the primary: checkpoint copy + log tail
        (reference learn flow: get_checkpoint -> storage_apply_checkpoint ->
        replay private log, SURVEY §3.5). `primary` is anything exposing
        fetch_learn_state() — a local Replica or an RPC peer proxy (the
        NFS-like learn file copy of config.ini:64-73)."""
        from ..runtime import events

        learning = counters.number(
            f"replica.{self.app_id}.{self.pidx}.learning")
        learning.set(1)
        events.emit("learn.start", gpid=f"{self.app_id}.{self.pidx}")
        t0 = time.monotonic()
        ok = False
        try:
            self._learn_from(primary)
            ok = True
        finally:
            learning.set(0)
            events.emit("learn.finish", severity="info" if ok else "error",
                        gpid=f"{self.app_id}.{self.pidx}", ok=ok,
                        dur_s=round(time.monotonic() - t0, 3),
                        committed=self.last_committed)  #: unguarded_ok post-learn snapshot for the event record; _learn_from already released the lock and the value only moves forward
            self._export_gauges()

    def _learn_from(self, primary):
        with self._learn_serial:
            self._learn_from_serialized(primary)

    def _learn_from_serialized(self, primary):
        with self._lock:
            self.status = LEARNER
            self._learning = True
            self._uncommitted.clear()
        try:
            if hasattr(primary, "prepare_learn_state"):
                self._learn_streamed(primary)
            else:  # legacy peer: monolithic whole-state copy
                self._learn_monolithic(primary)
        finally:
            with self._lock:
                self._learning = False

    def _learn_streamed(self, primary):
        """Block-shipped learn (ISSUE 13): manifest-diff handshake, then
        chunked delta streaming into learn_ckpt/ with BOTH locks released
        (the primary serves pinned immutable files, this replica rejects
        prepares via _learning), then a decree-anchored digest proof of
        the staged state, and only then a short swap critical section."""
        import shutil

        from . import learn as learn_mod
        from ..runtime import events
        from ..runtime.job_trace import JOB_TRACER

        t0 = time.perf_counter()
        ckpt_dir = os.path.join(self.path, "learn_ckpt")
        data_dir = os.path.join(self.path, "data")
        # each learn is ONE traced background job (ISSUE 16): prepare /
        # fetch waves / digest proof / swap are its hops, and the job id
        # rides the prepare RPC so the serving primary can attribute its
        # checkpoint pin to this learn's timeline
        with JOB_TRACER.job("learn", gpid=f"{self.app_id}.{self.pidx}",
                            learner=self.name):
            self._learn_streamed_traced(primary, learn_mod, events, shutil,
                                        ckpt_dir, data_dir, t0)

    def _learn_streamed_traced(self, primary, learn_mod, events, shutil,
                               ckpt_dir, data_dir, t0):
        from ..runtime.job_trace import JOB_TRACER

        # the delta handshake: what this replica already holds — staged
        # blocks from an interrupted ship (resume) plus the live engine's
        # current files (a re-learn that still has 99% of the SSTs). The
        # live manifest is computed ONCE and reused as stage_blocks'
        # link-reuse index — no second full-directory digest scan.
        delta_on = learn_mod.delta_enabled()
        live = learn_mod.dir_manifest(data_dir) if delta_on else []
        have = (learn_mod.dir_manifest(ckpt_dir) + live) if delta_on else []
        with JOB_TRACER.hop("learn.prepare", have=len(have)) as jh:
            st = primary.prepare_learn_state(have=have, delta=delta_on)
            jh["blocks"] = len(st["blocks"])
            jh["missing"] = len(st["missing"])
        try:
            with JOB_TRACER.hop("learn.fetch") as jh:
                stats = learn_mod.stage_blocks(
                    primary, st, ckpt_dir, delta=delta_on,
                    reuse={e["digest"]: os.path.join(data_dir, e["name"])
                           for e in live})
                jh.update({k: stats[k] for k in
                           ("fetched", "bytes", "skipped", "resumed")})
            with JOB_TRACER.hop("learn.tail"):
                tail_state = primary.fetch_learn_tail(st["learn_id"])
        finally:
            primary.finish_learn(st["learn_id"])
        verify = ""
        if st.get("digest"):
            # the shipped replica proves itself byte-consistent on
            # arrival BEFORE it may serve. DELTA learns take the
            # INCREMENTAL proof (ISSUE 14 satellite, learn follow-on c):
            # stage_blocks' running fold over the per-block digests it
            # verified equals the fold of the primary's manifest, so the
            # staged dir holds exactly the checkpoint's bytes — cost
            # O(delta), no record rescan per learn. A learn that reused
            # NOTHING (a fresh seed, or delta off) still pays the full
            # decree-anchored rescan: it is the trust anchor that
            # cross-checks the primary's logical digest against what was
            # actually shipped, once, before incremental re-learns lean
            # on it. Fold mismatch (or PEGASUS_LEARN_INCREMENTAL_DIGEST
            # =0) falls back to the rescan; the mismatch behavior is
            # unchanged — fail the learn loudly, never a silent
            # divergent serve.
            with JOB_TRACER.hop("learn.digest_proof") as jh:
                if learn_mod.incremental_digest_enabled() \
                        and stats["skipped"] + stats["resumed"] > 0 \
                        and stats.get("fold") \
                        and stats["fold"] == learn_mod.manifest_fold(
                            st["blocks"]):
                    verify = "incremental"
                    counters.rate(
                        "learn.verify.incremental_count").increment()
                else:
                    verify = "rescan"
                    counters.rate("learn.verify.rescan_count").increment()
                    from ..engine import EngineOptions
                    from ..engine.db import LsmEngine

                    ver = LsmEngine(ckpt_dir, EngineOptions(
                        backend="cpu", pidx=self.pidx))
                    try:
                        d = ver.state_digest(now=st["digest_now"],
                                             pmask=st["digest_pmask"])
                    finally:
                        ver.close()
                    if d["digest"] != st["digest"]:
                        raise ReplicaError(
                            f"{self.name}: shipped state digest mismatch at "
                            f"checkpoint decree {st['ckpt_decree']}: "
                            f"{d['digest']} != primary {st['digest']}")
                jh["mode"] = verify
        with JOB_TRACER.hop("learn.swap") as jh:
            replayed = self._swap_learned_state(ckpt_dir, tail_state)
            jh["replayed"] = replayed
        shutil.rmtree(ckpt_dir, ignore_errors=True)  # staged blocks are
        # hardlinked into data/ now; keeping them would feed stale names
        # into the NEXT learn's have-set
        counters.percentile("learn.ship.duration_us").set(
            int((time.perf_counter() - t0) * 1e6))
        events.emit("learn.ship", gpid=f"{self.app_id}.{self.pidx}",
                    decree=st["ckpt_decree"], fetched=stats["fetched"],
                    bytes=stats["bytes"], delta_skipped=stats["skipped"],
                    resumed=stats["resumed"], replayed=replayed,
                    verify=verify)

    def _learn_monolithic(self, primary):
        """Legacy whole-state learn (a peer without the block-ship
        surface): the transfer still runs with this replica's lock
        released — only the swap is a critical section."""
        state = primary.fetch_learn_state()
        ckpt_dir = os.path.join(self.path, "learn_ckpt")
        if os.path.exists(ckpt_dir):
            import shutil

            shutil.rmtree(ckpt_dir)
        os.makedirs(ckpt_dir)
        nbytes = 0
        for fname, blob in state["files"]:
            with open(os.path.join(ckpt_dir, fname), "wb") as f:
                f.write(blob)
            nbytes += len(blob)
        counters.rate("learn.ship.blocks").increment(len(state["files"]))
        counters.rate("learn.ship.bytes").increment(nbytes)
        self._swap_learned_state(ckpt_dir, state)

    def _swap_learned_state(self, ckpt_dir: str, tail_state: dict) -> int:
        """The learn's ONLY critical section: swap the staged checkpoint
        in as the serving engine, reset the plog, stage + apply the log
        tail above the checkpoint decree. -> tail mutations replayed."""
        replayed = 0
        with self._lock:
            self.server.close()
            from ..engine.db import LsmEngine

            engine = LsmEngine.apply_checkpoint(
                ckpt_dir, os.path.join(self.path, "data"),
                self.server.engine.opts)
            self.server = PegasusServer.__new__(PegasusServer)
            self.server.__init__(os.path.join(self.path, "data"),
                                 app_id=self.app_id, pidx=self.pidx,
                                 options=engine.opts, server=self.name,
                                 cluster_id=self.cluster_id)
            # the swap built a brand-new engine: re-arm the corruption
            # callout or post-learn bit-rot would go unreported
            self.server.engine.corruption_hook = self.corruption_hook
            self.plog.reset()
            self.last_committed = self.server.engine.last_committed_decree()
            self.last_prepared = self.last_committed
            # replay ONLY the log tail beyond the checkpoint decree —
            # the whole point of shipping compacted state
            for m in tail_state["tail"]:
                if m.decree <= self.last_prepared:
                    continue
                self.plog.append(m)
                self.last_prepared = m.decree
                self._uncommitted[m.decree] = m
                replayed += 1
            self._apply_up_to(min(tail_state["last_committed"],
                                  self.last_prepared))
            self.ballot = max(self.ballot, tail_state["ballot"])
            self.status = SECONDARY
        counters.rate("learn.replay.mutations").increment(replayed)
        return replayed

    # ------------------------------------------------------ learn: primary

    def prepare_learn_state(self, have=None, delta=None) -> dict:
        """Manifest-diff handshake, primary side (ISSUE 13): pin an
        immutable checkpoint (checkpoint GC + plog GC of covered
        segments held while pinned), diff its block manifest against the
        learner's `have` set, and return only the missing blocks'
        metadata plus the checkpoint's decree-anchored digest. The
        replica lock is held only for the watermark snapshot — never
        across checkpointing or file reads (the old fetch_learn_state
        stalled the prepare path for the whole transfer)."""
        from . import learn as learn_mod

        eng = self.server.engine
        ttl = learn_mod.pin_ttl_s()
        with eng.checkpoint_lock:
            # flush=False: snapshot the DURABLE state only. Sequential
            # learns (the balancer moving many partitions, repair
            # retries) then share ONE checkpoint dir and its cached
            # digest instead of forcing a memtable flush + a fresh
            # full-state scan per learn — the un-flushed window rides
            # the log tail, which is exactly what the tail is for
            decree = eng.sync_checkpoint(flush=False)
            ckpt = eng.get_checkpoint_dir(decree)
            token = eng.pin_checkpoint(decree, ttl_s=ttl)
        try:
            manifest = learn_mod.dir_manifest(ckpt)
            digest = (eng.checkpoint_digest(decree)
                      if learn_mod.verify_enabled() else {})
        except BaseException:
            eng.unpin_checkpoint(decree, token)
            raise
        with self._learn_lock:
            self._learn_next_id += 1
            learn_id = self._learn_next_id
            self._learn_pins[learn_id] = {
                "decree": decree, "dir": ckpt, "token": token,
                "expires": time.monotonic() + ttl}
        delta_on = learn_mod.delta_enabled() if delta is None else bool(delta)
        have_set = {(e["name"], e["digest"])
                    for e in (have or [])} if delta_on else set()
        missing = [e["name"] for e in manifest
                   if (e["name"], e["digest"]) not in have_set]
        with self._lock:
            ballot, committed = self.ballot, self.last_committed
        return {"learn_id": learn_id, "ckpt_decree": decree,
                "ballot": ballot, "last_committed": committed,
                "blocks": manifest, "missing": missing,
                "digest": digest.get("digest", ""),
                "digest_now": digest.get("now", 0),
                "digest_pmask": digest.get("pmask", 0)}

    def _learn_pin(self, learn_id: int, renew: bool = True) -> dict:
        """Resolve (and lease-renew) an active learn pin; expired or
        unknown pins fail the fetch loudly so the learner restarts its
        learn instead of shipping from a GC-racing checkpoint."""
        from . import learn as learn_mod

        now = time.monotonic()
        ttl = learn_mod.pin_ttl_s()
        snap = None
        with self._learn_lock:
            pin = self._learn_pins.get(learn_id)
            if pin is not None and now < pin["expires"]:
                if renew:
                    pin["expires"] = now + ttl
                snap = dict(pin)
        if snap is None:
            raise ReplicaError(
                f"{self.name}: learn {learn_id} expired/unknown")
        if renew:  # engine lease renewed OUTSIDE the leaf pin lock
            self.server.engine.renew_checkpoint_pin(snap["decree"],
                                                    snap["token"], ttl)
        return snap

    def fetch_learn_block(self, learn_id: int, name: str, offset: int,
                          length: int) -> dict:
        """Serve one chunk of one pinned checkpoint block — LOCK-FREE:
        pinned files are immutable (checkpoint hardlinks are independent
        dir entries) and held out of GC by the pin."""
        from ..runtime.fail_points import inject
        import zlib

        inject("learn.ship")  # chaos seam: a mid-ship abort on the primary
        pin = self._learn_pin(learn_id)
        path = os.path.join(pin["dir"], os.path.basename(name))
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(length)
        return {"data": data, "crc": zlib.crc32(data),
                "total": os.path.getsize(path)}

    def fetch_learn_chunks(self, learn_id: int, reqs) -> list:
        """In-process chunk wave (the RPC peer pipelines the same shape
        through call_many — learn.RemoteLearnSource)."""
        return [self.fetch_learn_block(learn_id, name, off, ln)
                for (name, off, ln) in reqs]

    def fetch_learn_tail(self, learn_id: int) -> dict:
        """Log tail above the pinned checkpoint decree + watermarks.
        The watermark snapshot is the only locked moment; the plog
        replay runs lock-free (segments covering the pin are held by
        gc_log's pin floor)."""
        pin = self._learn_pin(learn_id)
        with self._lock:
            ballot, committed = self.ballot, self.last_committed
        tail = list(self.plog.replay(pin["decree"]))
        return {"tail": tail, "last_committed": committed, "ballot": ballot}

    def finish_learn(self, learn_id: int) -> None:
        """Release the learn pin (GC of the checkpoint + covered log
        segments resumes). Idempotent; expiry covers a dead learner."""
        with self._learn_lock:
            pin = self._learn_pins.pop(learn_id, None)
        if pin is not None:
            self.server.engine.unpin_checkpoint(pin["decree"], pin["token"])

    def _live_learn_pin_floor(self) -> int:
        """Lowest pinned checkpoint decree (or a huge sentinel) — the
        plog GC floor while learns are in flight; expired pins reaped."""
        now = time.monotonic()
        dead = []
        with self._learn_lock:
            for lid, pin in list(self._learn_pins.items()):
                if now >= pin["expires"]:
                    dead.append(self._learn_pins.pop(lid))
            floor = min((p["decree"] for p in self._learn_pins.values()),
                        default=None)
        for pin in dead:
            self.server.engine.unpin_checkpoint(pin["decree"], pin["token"])
        return floor

    def learn_state(self) -> dict:
        """Learner-side learn snapshot (learn-status surface)."""
        with self._lock:
            return {"learning": self._learning, "status": self.status}

    def learn_pins(self) -> list:
        """Active primary-side learn pins (learn-status surface)."""
        now = time.monotonic()
        with self._learn_lock:
            return [{"learn_id": lid, "decree": p["decree"],
                     "expires_in_s": round(max(0.0, p["expires"] - now), 1)}
                    for lid, p in self._learn_pins.items()]

    def fetch_learn_state(self) -> dict:
        """Legacy monolithic learn state (old peers; the bench's
        monolithic A/B lane). Now pin-then-release: the checkpoint is
        pinned and every file read runs with NO replica lock held, so a
        learn can't stall this primary's prepare path for the duration
        of a multi-MB read (ISSUE 13 satellite)."""
        st = self.prepare_learn_state(have=(), delta=False)
        lid = st["learn_id"]
        try:
            pin = self._learn_pin(lid, renew=False)
            files = []
            for e in st["blocks"]:
                with open(os.path.join(pin["dir"], e["name"]), "rb") as f:
                    files.append((e["name"], f.read()))
            tail_state = self.fetch_learn_tail(lid)
            return {"files": files, "tail": tail_state["tail"],
                    "last_committed": tail_state["last_committed"],
                    "ballot": tail_state["ballot"]}
        finally:
            self.finish_learn(lid)

    # ------------------------------------------------------------- plumbing

    def gc_log(self, flush: bool = False):
        """Drop log segments the durable SSTs cover. flush=True forces the
        memtable down first (tests); the maintenance timer must NOT — a
        periodic forced flush would churn tiny L0 files on idle tables.
        Active duplications hold the log at their confirmed decree: a
        restarted/promoted shipper must be able to catch_up() from plog
        (the reference keeps plog for dup the same way)."""
        if flush:
            self.server.engine.flush()
        floor = self.server.engine.last_durable_decree()
        # active learn pins hold the log at their checkpoint decree: the
        # learner's tail fetch replays (pin decree, ...] and a segment
        # GC'd out from under it would open an unreplayable gap
        pin_floor = self._live_learn_pin_floor()
        if pin_floor is not None:
            floor = min(floor, pin_floor)
        # Per dup entry the holdback decree is the freshest confirmed point
        # we know: our own shipper's progress when we run one (primary),
        # else the meta-confirmed decree the env carries (secondaries hold
        # the log too — on promotion the new primary catches up from ITS
        # plog, so gc'ing past that floor would open a duplication gap; the
        # meta re-pushes refreshed entries periodically so this floor
        # advances on stable clusters instead of pinning the log at 0).
        entries = {e["dupid"]: e for e in self._dup_env_entries()
                   if e.get("status") in ("init", "start", "pause")}
        dups = dict(self.duplicators)
        for dupid, e in entries.items():
            conf = int(e.get("confirmed", {}).get(str(self.pidx), 0))
            d = dups.get(dupid)
            floor = min(floor, max(conf, d.last_shipped_decree) if d else conf)
        for dupid, d in dups.items():
            if dupid not in entries:  # shipper ahead of the env snapshot
                floor = min(floor, d.last_shipped_decree)
        self.plog.gc(floor)

    def _dup_env_entries(self) -> list:
        import json

        from ..base import consts

        try:
            return json.loads(
                self.server.app_envs.get(consts.ENV_DUPLICATION_KEY, "[]"))
        except ValueError:
            return []

    def close(self):
        for dupid, d in self.duplicators.items():
            d.stop()
            counters.remove(f"dup.lag.{self.app_id}.{self.pidx}.{dupid}")
        self.duplicators.clear()
        # unregister this partition's lag gauges: a closed (rebalanced
        # away) replica's frozen values must not keep feeding the
        # collector's cluster worst-offender series
        for name in ("inflight", "backlog", "committed_decree",
                     "applied_decree", "secondary_gap_max", "learning"):
            counters.remove(f"replica.{self.app_id}.{self.pidx}.{name}")
        for name in ("l0_files", "debt_bytes", "pending_installs"):
            counters.remove(
                f"engine.compact.{self.app_id}.{self.pidx}.{name}")
        if self._prep_pool is not None:
            self._prep_pool.shutdown(wait=False)
            self._prep_pool = None
        self.plog.close()
        self.server.close()
