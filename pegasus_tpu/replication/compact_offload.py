"""Rack-scale compaction offload: one device-owning compaction service
serving many CPU-only replica nodes (ISSUE 14).

Every replica node used to need its own chip to compact on device;
production racks don't ship that way. LUDA (PAPERS.md) shows the winning
deployment shape is compaction offload to a shared accelerator host, and
this module builds it out of machinery the repo already trusts:

  * **Service** (``CompactOffloadService``): one process per TPU host,
    owning the device. Tenants open a job with a manifest of packed runs
    (``ops.packing.pack_run_bytes`` — the pack/serialize boundary), ship
    the runs as bounded CRC-checked chunks (the PR 13 learn-plane
    streaming shape: content-addressed staging, so an interrupted ship
    RESUMES — a retry ships only what never landed), then ask for the
    merge. The service multiplexes tenants across whatever it owns via
    ``parallel.compact_blocks_meshed`` (all_to_all sharded kernel on a
    multi-chip mesh, guarded single-chip merge otherwise) under its own
    admission gate (at most ``PEGASUS_OFFLOAD_MAX_CONCURRENT`` merges in
    flight; the rest are refused, not queued — the tenant's lane policy
    decides whether to retry or compact locally). Jobs are TTL leases:
    a dead tenant's job dir is reaped, never wedges the service.

  * **Client** (``offload_compact_blocks``): the node-side merge entry
    ``engine/db.py`` routes through when a scheduler placement names a
    remote service. lane_guard semantics extend across the wire — the
    whole ship/merge/fetch round runs under ``OFFLOAD_LANE_GUARD``
    (deadline, bounded retries, circuit breaker, counters
    ``offload.lane.*``), whose fallback is the node's LOCAL cpu
    compaction, byte-identical by construction: the service merges with
    user rules and the default-TTL rewrite masked off and the client
    applies them after return, exactly the ``sharded_compact_block``
    post-filter pattern. A dead, slow or breaker-open service therefore
    costs latency on one merge, never availability — and never different
    bytes.

Placement (WHERE) rides the same leased policy tokens as timing (WHEN):
``collector/compact_scheduler.fold_decisions`` assigns partitions to
services with free device budget, ``compact-sched-policy`` delivers
``where`` alongside ``policy``, and ``LsmEngine.set_offload_target``
holds it as a TTL lease — a dead scheduler expires nodes back to local
compaction, the same degradation story every other token has.

Chaos seam: the ``compact.offload`` fail point fires at the ship, merge
and return (fetch) stages on both sides of the wire.
"""

import hashlib
import json
import os
import shutil
import threading
import time
import zlib
from dataclasses import replace

from ..ops.compact import CompactOptions, CompactResult, apply_post_filters
from ..ops.packing import pack_run_bytes, unpack_run_bytes
from ..rpc import codec
from ..rpc import messages as rpc_msg
from ..rpc.transport import ConnectionPool, RpcError, RpcServer
from ..runtime import events, lockrank
from ..runtime.fail_points import inject
from ..runtime.lane_guard import LaneGuard, LaneGuardConfig
from ..runtime.perf_counters import counters
from ..runtime.job_trace import JOB_TRACER
from ..runtime.remote_command import RemoteCommandService
from ..runtime.tracing import COMPACT_TRACER as _TRACE

RPC_COMPACT_OFFLOAD_BEGIN = "RPC_COMPACT_OFFLOAD_BEGIN"
RPC_COMPACT_OFFLOAD_SHIP = "RPC_COMPACT_OFFLOAD_SHIP"
RPC_COMPACT_OFFLOAD_MERGE = "RPC_COMPACT_OFFLOAD_MERGE"
RPC_COMPACT_OFFLOAD_FETCH = "RPC_COMPACT_OFFLOAD_FETCH"
RPC_COMPACT_OFFLOAD_FINISH = "RPC_COMPACT_OFFLOAD_FINISH"

# CompactOptions fields that cross the wire. user_ops (parsed rule
# objects) and default_ttl deliberately do NOT: they run tenant-side as
# post filters, so the service needs no rule vocabulary and the output
# stays byte-identical to the tenant's local merge.
_WIRE_OPT_FIELDS = ("now", "pidx", "partition_mask", "bottommost",
                    "filter", "prefix_u32", "runs_sorted")


class OffloadError(ConnectionError):
    """An offload round failed (service dead/busy, chunk CRC, digest
    mismatch, expired job). ConnectionError subclass so the lane guard's
    retry/fallback treats it like any other transient device error."""


def chunk_bytes() -> int:
    """PEGASUS_OFFLOAD_CHUNK_BYTES: bounded ship/fetch chunk size."""
    return max(4096, int(os.environ.get("PEGASUS_OFFLOAD_CHUNK_BYTES",
                                        str(1 << 20))))


def rpc_timeout_s() -> float:
    """PEGASUS_OFFLOAD_RPC_TIMEOUT_S: per-RPC bound for begin/ship/fetch
    waves (the merge call gets its own, longer bound)."""
    return float(os.environ.get("PEGASUS_OFFLOAD_RPC_TIMEOUT_S", "30"))


def merge_timeout_s() -> float:
    """PEGASUS_OFFLOAD_MERGE_TIMEOUT_S: bound on the blocking merge RPC
    (covers the service-side device merge incl. a cold jit)."""
    return float(os.environ.get("PEGASUS_OFFLOAD_MERGE_TIMEOUT_S", "300"))


def _md5(data: bytes) -> str:
    # transfer-dedup content address, not a security boundary (the same
    # contract as learn.file_digest); corruption on the wire is caught by
    # the per-chunk CRC and this digest together
    return hashlib.md5(data).hexdigest()


def wire_opts(opts: CompactOptions) -> str:
    """The merge options a tenant ships — `now` must already be resolved
    (both sides' TTL drops must agree on the clock)."""
    return json.dumps({f: getattr(opts, f) for f in _WIRE_OPT_FIELDS},
                      sort_keys=True)


def opts_from_wire(opts_json: str, backend: str) -> CompactOptions:
    raw = json.loads(opts_json or "{}")
    kw = {f: raw[f] for f in _WIRE_OPT_FIELDS if f in raw}
    return CompactOptions(backend=backend, user_ops=(), default_ttl=0, **kw)


def _warm_offload_counters() -> None:
    """Literal registrations for every offload counter (the guard and
    the client increment through prefixes/f-strings): /metrics shows
    zeros before the first merge and tools/analyze ties README rows to
    registrations."""
    counters.rate("offload.lane.fallback_count")
    counters.rate("offload.lane.retry_count")
    counters.rate("offload.lane.deadline_abandon_count")
    counters.rate("offload.lane.breaker_trip_count")
    counters.number("offload.lane.breaker_open")
    counters.rate("offload.client.merge_count")
    counters.rate("offload.client.ship_bytes")
    counters.rate("offload.client.ship_blocks")
    counters.rate("offload.client.skipped_blocks")
    counters.rate("offload.client.fetch_bytes")


_warm_offload_counters()

# The wire lane: its OWN breaker/totals (counters ``offload.lane.*``), so
# a dead offload service degrades remote merges to local cpu without
# touching the node's other lanes. The 120 s default deadline bounds a
# whole ship+merge+fetch round even if every per-RPC timeout is dodged by
# a slow-dripping service.
OFFLOAD_LANE_GUARD = LaneGuard(
    LaneGuardConfig.from_env("PEGASUS_OFFLOAD_LANE", deadline_s=120.0),
    metric_prefix="offload.lane")


# ================================================================ service


class CompactOffloadService:
    """One device-owning compaction service process (see module
    docstring). Construct, then ``start()``; ``address`` is what tenants
    and the scheduler's placement scrape dial."""

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0,
                 backend: str = "cpu", mesh=None, max_concurrent: int = None,
                 job_ttl_s: float = None):
        self.root = root
        self.backend = backend
        self.mesh = mesh
        self.max_concurrent = max(1, int(
            os.environ.get("PEGASUS_OFFLOAD_MAX_CONCURRENT", "2")
            if max_concurrent is None else max_concurrent))
        self.job_ttl_s = float(
            os.environ.get("PEGASUS_OFFLOAD_JOB_TTL_S", "600")
            if job_ttl_s is None else job_ttl_s)
        self._blocks_dir = os.path.join(root, "blocks")
        self._jobs_dir = os.path.join(root, "jobs")
        os.makedirs(self._blocks_dir, exist_ok=True)
        os.makedirs(self._jobs_dir, exist_ok=True)
        # leaf lock over job/staging state; never held across a merge,
        # a disk write or an RPC
        self._lock = lockrank.named_lock("offload.service")
        self._jobs = {}       #: guarded_by self._lock
        self._next_job = 0    #: guarded_by self._lock
        self._running = 0     #: guarded_by self._lock
        # digest -> {"got": set(offsets), "size": int} for blocks mid-ship
        self._inflight = {}   #: guarded_by self._lock
        self._merge_total = 0  #: guarded_by self._lock
        self._c_jobs = counters.number("offload.service.jobs_active")
        self._c_running = counters.number("offload.service.running_merges")
        self._c_merges = counters.rate("offload.service.merge_count")
        self._c_rejects = counters.rate("offload.service.reject_count")
        self._c_in = counters.rate("offload.service.bytes_in")
        self._c_out = counters.rate("offload.service.bytes_out")
        self._c_resumed = counters.rate("offload.service.resumed_blocks")
        self.rpc = RpcServer(host, port)
        self.rpc.register(RPC_COMPACT_OFFLOAD_BEGIN, self._on_begin)
        self.rpc.register(RPC_COMPACT_OFFLOAD_SHIP, self._on_ship)
        self.rpc.register(RPC_COMPACT_OFFLOAD_MERGE, self._on_merge)
        self.rpc.register(RPC_COMPACT_OFFLOAD_FETCH, self._on_fetch)
        self.rpc.register(RPC_COMPACT_OFFLOAD_FINISH, self._on_finish)
        self.commands = RemoteCommandService()
        self.commands.register_defaults(node_kind="compact_offload",
                                        describe=self.status)
        self.commands.register("offload-status",
                               lambda a: json.dumps(self.status()))
        self.rpc.register("RPC_CLI_CLI_CALL", self.commands.rpc_handler)
        self.address = f"{self.rpc.address[0]}:{self.rpc.address[1]}"

    def start(self) -> "CompactOffloadService":
        self.rpc.start()
        return self

    def stop(self) -> None:
        self.rpc.stop()

    # ------------------------------------------------------------- status

    def status(self) -> dict:
        """The placement scrape: free device budget (merge slots) is what
        the scheduler's fold turns into (when, where) pairs."""
        with self._lock:
            jobs = len(self._jobs)
            running = self._running
            merges = self._merge_total
        staged = 0
        try:
            staged = sum(e.stat().st_size for e in os.scandir(self._blocks_dir)
                         if e.is_file())
        except OSError:
            pass
        return {"address": self.address, "backend": self.backend,
                "max_concurrent": self.max_concurrent,
                "running_merges": running,
                "free_slots": max(0, self.max_concurrent - running),
                "jobs": jobs, "merges_done": merges,
                "staged_bytes": staged}

    # ------------------------------------------------------------ plumbing

    def _block_path(self, digest: str) -> str:
        return os.path.join(self._blocks_dir, digest)

    def _trace(self, job: dict, name: str, **attrs) -> None:
        """Record one service-side hop for the tenant's traced job
        (ISSUE 16) — plain records kept in the job dict and returned in
        the merge response for the tenant to stitch, NOT recorded into
        this process's JOB_TRACER (in a onebox both sides share the
        tracer and the hops would double-record)."""
        if not job.get("trace_job"):
            return
        rec = {"name": name, "ts": time.time(), "duration_us": 0}
        rec.update(attrs)
        with self._lock:
            job["spans"].append(rec)

    def _job(self, job_id: int) -> dict:
        now = time.monotonic()
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                raise OffloadError(f"offload job {job_id} expired/unknown")
            job["expires"] = now + self.job_ttl_s  # every RPC renews
            return job

    def _reap_locked(self, now: float) -> None:  #: requires self._lock
        for jid in [j for j, job in self._jobs.items()
                    if now >= job["expires"]]:
            job = self._jobs.pop(jid)
            shutil.rmtree(job["dir"], ignore_errors=True)
        self._c_jobs.set(len(self._jobs))

    def _gc_blocks(self) -> None:
        """Drop staged runs (and torn .part files, and their in-memory
        staging state) no live job references once their TTL lapsed —
        content-addressed blocks outlive jobs ON PURPOSE (that is what
        makes a retry's ship resumable), but an abandoned mid-ship
        tenant must not leak disk or ``_inflight`` entries forever."""
        with self._lock:
            live = {e.digest for job in self._jobs.values()
                    for e in job["runs"]}
        cutoff = time.time() - self.job_ttl_s
        try:
            entries = list(os.scandir(self._blocks_dir))
        except OSError:
            return
        for e in entries:
            digest = e.name[:-5] if e.name.endswith(".part") else e.name
            try:
                if digest not in live and e.stat().st_mtime < cutoff:
                    os.unlink(e.path)
                    with self._lock:
                        self._inflight.pop(digest, None)
            except OSError:
                continue
        # inflight entries whose .part never landed and is gone
        # (abandoned before any GC-able file aged out, or unlinked by a
        # failed finalize) go with the job references; the stat runs
        # outside the leaf lock
        with self._lock:
            stale = [d for d in self._inflight if d not in live]
        for digest in stale:
            if not os.path.exists(self._block_path(digest) + ".part"):
                with self._lock:
                    self._inflight.pop(digest, None)

    # ------------------------------------------------------------ handlers

    def _on_begin(self, header, body) -> bytes:
        req = codec.decode(rpc_msg.OffloadBeginRequest, body)
        inject("compact.offload")  # chaos seam: ship stage, service side
        now = time.monotonic()
        with self._lock:
            self._reap_locked(now)
            if len(self._jobs) >= self.max_concurrent * 4:
                self._c_rejects.increment()
                events.emit("offload.reject", severity="warn",
                            tenant=req.tenant, gpid=req.gpid,
                            reason="job_cap", jobs=len(self._jobs))
                return codec.encode(rpc_msg.OffloadBeginResponse(
                    error=1, error_text=f"busy: {len(self._jobs)} jobs "
                    f"active (cap {self.max_concurrent * 4})"))
            self._next_job += 1
            job_id = self._next_job
            job = {"id": job_id, "tenant": req.tenant, "gpid": req.gpid,
                   "runs": list(req.runs), "opts_json": req.opts_json,
                   "dir": os.path.join(self._jobs_dir, str(job_id)),
                   "outputs": [], "stats": {},
                   # causal tracing (ISSUE 16): the tenant's job-trace id
                   # and the hop records this service makes for it —
                   # returned in the merge response for the tenant to
                   # stitch home (NOT via the process tracer: in a onebox
                   # both sides share it and would double-record)
                   "trace_job": req.job, "spans": [],
                   "expires": now + self.job_ttl_s}
            self._jobs[job_id] = job
            self._c_jobs.set(len(self._jobs))
        self._gc_blocks()
        staged = []
        for e in req.runs:
            p = self._block_path(e.digest)
            try:
                if os.path.getsize(p) == e.size:
                    staged.append(e.name)
                    self._c_resumed.increment()
            except OSError:
                continue
        self._trace(job, "offload.svc.begin", runs=len(req.runs),
                    resumed=len(staged))
        return codec.encode(rpc_msg.OffloadBeginResponse(
            job_id=job_id, staged=staged))

    def _on_ship(self, header, body) -> bytes:
        req = codec.decode(rpc_msg.OffloadShipRequest, body)
        try:
            inject("compact.offload")  # chaos seam: per shipped chunk
            job = self._job(req.job_id)
            entry = next((e for e in job["runs"] if e.name == req.name), None)
            if entry is None:
                raise OffloadError(f"unknown run {req.name!r}")
            if zlib.crc32(req.data) != req.crc:
                raise OffloadError(f"chunk CRC mismatch for {req.name}"
                                   f"@{req.offset}")
            landed = self._land_chunk(entry, req.offset, req.data)
        except (OffloadError, OSError, ValueError) as e:
            return codec.encode(rpc_msg.OffloadShipResponse(
                error=1, error_text=repr(e)))
        self._c_in.increment(len(req.data))
        return codec.encode(rpc_msg.OffloadShipResponse(landed=landed))

    def _land_chunk(self, entry, offset: int, data: bytes) -> bool:
        """Write one chunk at its offset into the content-addressed
        staging file; when every byte has arrived, verify the whole-file
        digest and atomically publish. Chunks may arrive out of order
        (the client's call_many wave fans across the RPC pool). -> True
        once the block is fully landed and verified."""
        final = self._block_path(entry.digest)
        part = final + ".part"
        with self._lock:
            if os.path.exists(final):
                return True  # a sibling shipper already landed it
            st = self._inflight.setdefault(entry.digest,
                                           {"got": set(), "size": entry.size,
                                            "finalizing": False})
            if st["got"] and not os.path.exists(part):
                # stale state from an ABANDONED ship whose .part was
                # GC'd (or finalize-failed): a fresh shipper must start
                # with an empty got-set, or the first chunk would read
                # as "complete" and fail the whole round on a torn file
                st["got"] = set()
                st["finalizing"] = False
        open(part, "ab").close()  # ensure exists before the r+b seek-write
        with open(part, "r+b") as f:
            f.seek(offset)
            f.write(data)
        with self._lock:
            # the got-set records a chunk only AFTER its bytes are in the
            # file, and exactly ONE handler finalizes (chunks of a wave
            # land on concurrent pool threads; the last writers race here)
            st["got"].add((offset, len(data)))
            complete = (sum(ln for _, ln in st["got"]) >= entry.size
                        and not st["finalizing"])
            if complete:
                st["finalizing"] = True
        if not complete:
            return os.path.exists(final)
        try:
            with open(part, "rb") as f:
                whole = f.read()
        except OSError:
            return os.path.exists(final)  # a sibling already published
        if len(whole) != entry.size or _md5(whole) != entry.digest:
            # torn/overlapping ship: drop the staging state so a retry
            # starts the block clean instead of re-verifying garbage
            with self._lock:
                self._inflight.pop(entry.digest, None)
            try:
                os.unlink(part)
            except OSError:
                pass
            raise OffloadError(f"staged run {entry.name} digest mismatch")
        os.replace(part, final)
        with self._lock:
            self._inflight.pop(entry.digest, None)
        return True

    def _on_merge(self, header, body) -> bytes:
        req = codec.decode(rpc_msg.OffloadMergeRequest, body)
        try:
            inject("compact.offload")  # chaos seam: merge stage
            job = self._job(req.job_id)
            with self._lock:
                if job["outputs"]:
                    # idempotent: a retried merge call returns the done job
                    return codec.encode(rpc_msg.OffloadMergeResponse(
                        outputs=list(job["outputs"]),
                        stats_json=json.dumps(job["stats"]),
                        spans_json=json.dumps(job["spans"])))
                if self._running >= self.max_concurrent:
                    # admission gate: refuse, never queue — the tenant's
                    # lane policy decides between retry and local cpu
                    self._c_rejects.increment()
                    events.emit("offload.reject", severity="warn",
                                tenant=job["tenant"], gpid=job["gpid"],
                                reason="merge_cap", running=self._running)
                    return codec.encode(rpc_msg.OffloadMergeResponse(
                        error=1, error_text=f"busy: {self._running} merges "
                        f"in flight (cap {self.max_concurrent})"))
                self._running += 1
                self._c_running.set(self._running)
            try:
                outputs, stats = self._merge_job(job)
            finally:
                with self._lock:
                    self._running -= 1
                    self._c_running.set(self._running)
        except (OffloadError, OSError, ValueError) as e:
            return codec.encode(rpc_msg.OffloadMergeResponse(
                error=1, error_text=repr(e)))
        with self._lock:
            spans = list(job["spans"])
        return codec.encode(rpc_msg.OffloadMergeResponse(
            outputs=outputs, stats_json=json.dumps(stats),
            spans_json=json.dumps(spans)))

    def _merge_job(self, job: dict) -> tuple:
        """Load the job's staged runs (manifest order = merge priority),
        merge across whatever this host owns, publish the packed output
        under the job dir. -> (outputs manifest, stats)."""
        t0 = time.perf_counter()
        blocks = []
        nbytes = 0
        for e in job["runs"]:
            try:
                with open(self._block_path(e.digest), "rb") as f:
                    data = f.read()
            except OSError:
                raise OffloadError(f"run {e.name} not staged (re-begin)")
            if _md5(data) != e.digest:
                raise OffloadError(f"staged run {e.name} corrupt on disk")
            nbytes += len(data)
            blocks.append(unpack_run_bytes(data))
        self._trace(job, "offload.svc.load", runs=len(blocks),
                    nbytes=nbytes,
                    duration_us=int((time.perf_counter() - t0) * 1e6))
        from ..parallel import compact_blocks_meshed

        opts = opts_from_wire(job["opts_json"], self.backend)
        t_merge = time.perf_counter()
        result = compact_blocks_meshed(blocks, opts, self.mesh)
        self._trace(job, "offload.svc.merge",
                    records_in=sum(b.n for b in blocks),
                    records_out=result.block.n,
                    duration_us=int((time.perf_counter() - t_merge) * 1e6))
        out_bytes = pack_run_bytes(result.block)
        os.makedirs(job["dir"], exist_ok=True)
        with open(os.path.join(job["dir"], "out.0"), "wb") as f:
            f.write(out_bytes)
        outputs = [rpc_msg.LearnBlockEntry("out.0", len(out_bytes),
                                           _md5(out_bytes))]
        stats = dict(result.stats)
        with self._lock:
            job["outputs"] = list(outputs)
            job["stats"] = stats
            self._merge_total += 1
        self._c_merges.increment()
        events.emit("offload.merge", tenant=job["tenant"], gpid=job["gpid"],
                    records_in=stats.get("input_records", 0),
                    records_out=stats.get("output_records", 0),
                    ms=round((time.perf_counter() - t0) * 1e3, 1))
        return outputs, stats

    def _on_fetch(self, header, body) -> bytes:
        req = codec.decode(rpc_msg.OffloadFetchRequest, body)
        try:
            inject("compact.offload")  # chaos seam: return (fetch) stage
            job = self._job(req.job_id)
            path = os.path.join(job["dir"], os.path.basename(req.name))
            with open(path, "rb") as f:
                f.seek(req.offset)
                data = f.read(req.length)
            total = os.path.getsize(path)
        except (OffloadError, OSError) as e:
            return codec.encode(rpc_msg.LearnFetchResponse(
                error=1, error_text=repr(e)))
        self._c_out.increment(len(data))
        return codec.encode(rpc_msg.LearnFetchResponse(
            data=data, crc=zlib.crc32(data), total=total))

    def _on_finish(self, header, body) -> bytes:
        req = codec.decode(rpc_msg.OffloadFinishRequest, body)
        with self._lock:
            job = self._jobs.pop(req.job_id, None)
            self._c_jobs.set(len(self._jobs))
        if job is not None:
            shutil.rmtree(job["dir"], ignore_errors=True)
        return codec.encode(rpc_msg.OffloadShipResponse(landed=True))


# ================================================================= client

# one pool per tenant process: offload traffic multiplexes the same
# connection per service like any other peer
_POOL = ConnectionPool()


def _call(addr: str, code: str, req, resp_cls, timeout: float = None):
    host, _, port = addr.rpartition(":")
    try:
        conn = _POOL.get((host, int(port)))
        _, body = conn.call(code, codec.encode(req),
                            timeout=rpc_timeout_s() if timeout is None
                            else timeout)
    except (RpcError, OSError, ValueError) as e:
        raise OffloadError(f"{code} to {addr}: {e}")
    resp = codec.decode(resp_cls, body)
    if resp.error:
        raise OffloadError(f"{code}: {resp.error_text}")
    return resp


def _call_wave(addr: str, calls: list, what: str) -> list:
    try:
        host, _, port = addr.rpartition(":")
        return _POOL.get((host, int(port))).call_many(
            calls, timeout=rpc_timeout_s())
    except (RpcError, OSError) as e:
        raise OffloadError(f"{what} {addr}: {e}")


def _ship_runs(addr: str, job_id: int, entries, payloads, staged) -> dict:
    """Ship every run the service does not already hold, as bounded
    CRC'd chunks pipelined through call_many waves (the learn plane's
    chunk_waves grid). -> stats."""
    from .learn import chunk_waves

    shipped = skipped = nbytes = 0
    c_blocks = counters.rate("offload.client.ship_blocks")
    c_skip = counters.rate("offload.client.skipped_blocks")
    c_bytes = counters.rate("offload.client.ship_bytes")
    for entry, payload in zip(entries, payloads):
        if entry.name in staged:
            skipped += 1
            c_skip.increment()
            continue
        inject("compact.offload")  # chaos seam: per shipped run
        for wave in chunk_waves(entry.size, chunk_bytes()):
            calls = []
            for off, ln in wave:
                data = payload[off:off + ln]
                calls.append((RPC_COMPACT_OFFLOAD_SHIP, codec.encode(
                    rpc_msg.OffloadShipRequest(
                        job_id=job_id, name=entry.name, offset=off,
                        data=data, crc=zlib.crc32(data)))))
            for _, rbody in _call_wave(addr, calls, "ship to"):
                resp = codec.decode(rpc_msg.OffloadShipResponse, rbody)
                if resp.error:
                    raise OffloadError(f"ship failed: {resp.error_text}")
        shipped += 1
        nbytes += entry.size
        c_blocks.increment()
    c_bytes.increment(nbytes)
    return {"shipped_runs": shipped, "skipped_runs": skipped,
            "shipped_bytes": nbytes}


def _fetch_output(addr: str, job_id: int, entry) -> bytes:
    """Stream one merged output block back (per-chunk CRC + whole-block
    digest), pipelined through call_many waves on the same grid."""
    from .learn import chunk_waves

    inject("compact.offload")  # chaos seam: return stage, client side
    parts = []
    for wave in chunk_waves(entry.size, chunk_bytes()):
        calls = [(RPC_COMPACT_OFFLOAD_FETCH, codec.encode(
            rpc_msg.OffloadFetchRequest(
                job_id=job_id, name=entry.name, offset=off, length=ln)))
            for off, ln in wave]
        for _, rbody in _call_wave(addr, calls, "fetch from"):
            resp = codec.decode(rpc_msg.LearnFetchResponse, rbody)
            if resp.error:
                raise OffloadError(f"fetch failed: {resp.error_text}")
            if zlib.crc32(resp.data) != resp.crc:
                raise OffloadError(f"fetch chunk CRC mismatch ({entry.name})")
            parts.append(resp.data)
    data = b"".join(parts)
    if len(data) != entry.size or _md5(data) != entry.digest:
        raise OffloadError(f"fetched output {entry.name} digest mismatch")
    counters.rate("offload.client.fetch_bytes").increment(len(data))
    return data


def _offload_once(blocks, opts: CompactOptions, addr: str,
                  tenant: str) -> CompactResult:
    """One remote ship/merge/fetch round (the lane guard retries this
    whole function; content-addressed staging makes a retry resume)."""
    runs = [b for b in blocks if b.n]
    payloads = [pack_run_bytes(b) for b in runs]
    entries = [rpc_msg.LearnBlockEntry(f"run.{i}", len(p), _md5(p))
               for i, p in enumerate(payloads)]
    # the causal job id crosses the wire (ISSUE 16): the service records
    # its own hops against it and returns them on merge for stitching
    trace_job = JOB_TRACER.current() or ""
    with _TRACE.span("offload.ship", records=sum(b.n for b in runs),
                     nbytes=sum(len(p) for p in payloads)), \
            JOB_TRACER.hop("offload.ship", service=addr,
                           nbytes=sum(len(p) for p in payloads)) as jh:
        begin = _call(addr, RPC_COMPACT_OFFLOAD_BEGIN,
                      rpc_msg.OffloadBeginRequest(
                          tenant=tenant, gpid=f"{opts.pidx}",
                          runs=entries, opts_json=wire_opts(opts),
                          job=trace_job),
                      rpc_msg.OffloadBeginResponse)
        ship = _ship_runs(addr, begin.job_id, entries, payloads,
                          set(begin.staged))
        jh.update(ship)
    try:
        with _TRACE.span("offload.merge", records=sum(b.n for b in runs)), \
                JOB_TRACER.hop("offload.merge", service=addr):
            inject("compact.offload")  # chaos seam: merge stage, client side
            m = _call(addr, RPC_COMPACT_OFFLOAD_MERGE,
                      rpc_msg.OffloadMergeRequest(job_id=begin.job_id),
                      rpc_msg.OffloadMergeResponse,
                      timeout=merge_timeout_s())
        if trace_job and m.spans_json:
            # one timeline, two hosts: the service's view comes home in
            # the response and lands origin-tagged next to our own hops
            try:
                JOB_TRACER.stitch(trace_job, json.loads(m.spans_json),
                                  origin=addr)
            except ValueError:
                pass  # a torn spans payload is diagnostic-only
        with _TRACE.span("offload.fetch",
                         nbytes=sum(e.size for e in m.outputs)) as sp, \
                JOB_TRACER.hop("offload.fetch", service=addr,
                               nbytes=sum(e.size for e in m.outputs)):
            out_parts = [_fetch_output(addr, begin.job_id, e)
                         for e in m.outputs]
            out = unpack_run_bytes(out_parts[0]) if out_parts else None
            sp["records"] = out.n if out is not None else 0
    finally:
        try:
            _call(addr, RPC_COMPACT_OFFLOAD_FINISH,
                  rpc_msg.OffloadFinishRequest(job_id=begin.job_id),
                  rpc_msg.OffloadShipResponse)
        except OffloadError:
            pass  # the job TTL covers an unreachable service
    from ..engine.block import KVBlock

    out = out if out is not None else KVBlock.empty()
    # tenant-side post passes (user rules, default-TTL rewrite) — the
    # sharded_compact_block pattern; the service merged with them masked
    out = apply_post_filters(out, opts, opts.now)
    stats = json.loads(m.stats_json or "{}")
    stats.update(ship)
    stats.update({"offloaded": True, "service": addr,
                  "output_records": out.n,
                  "fetched_bytes": sum(e.size for e in m.outputs)})
    counters.rate("offload.client.merge_count").increment()
    return CompactResult(out, stats)


def offload_compact_blocks(blocks, opts: CompactOptions, addr: str,
                           tenant: str = "",
                           guard: LaneGuard = None) -> CompactResult:
    """Node-side merge entry: compact `blocks` on the remote offload
    service at `addr`, byte-identical to ``compact_blocks(blocks, opts)``
    on cpu. Runs under OFFLOAD_LANE_GUARD: a dead/slow/breaker-open
    service falls back to the LOCAL cpu merge — latency, never
    availability, never different bytes."""
    from ..ops.compact import compact_blocks

    guard = OFFLOAD_LANE_GUARD if guard is None else guard
    # resolve the clock ONCE: remote kernel drops and local post filters
    # (and the cpu fallback) must agree on `now` or TTL edges diverge
    opts = replace(opts, now=opts.resolved_now())

    def _remote() -> CompactResult:
        return _offload_once(blocks, opts, addr, tenant)

    def _local() -> CompactResult:
        return compact_blocks(blocks, replace(opts, backend="cpu"))

    return guard.run(_remote, _local, op="offload_compact")
