"""Shared-nothing partition-group executors: the node-level GIL shatter.

BASELINE showed the serving stack 1-core-bound: YCSB-A peaked ~5.7k ops/s
at 8 client threads because every partition's reads, writes, codec work
and engine apply shared ONE interpreter. The reference Pegasus gets its
per-node scaling from rDSN's shared-nothing per-partition task engine
(SURVEY §L0): partitions never share an execution context.

This module is that architecture for the Python build. A serving node
with ``PEGASUS_SERVE_GROUPS=N`` runs:

  parent (this class, GroupedReplicaNode)
    - binds the node's PUBLIC address (what the meta routes clients to)
    - spawns N group-worker processes; worker g owns every
      (app_id, pidx) with ``group_of(app_id, pidx, N) == g``
      (pidx % N — consistent with replica_service's per-partition routing)
    - acceptor/router: a connection whose first frame is SHARDED
      (RpcHeader.sharded — the ConnectionPool's one-partition-per-
      connection shard keys) is handed to the owning worker wholesale via
      SCM_RIGHTS fd passing: after the handoff the parent is OUT of the
      data path and the partition's whole request loop runs under the
      worker's own GIL. Unsharded connections (meta lifecycle, shell,
      legacy clients) stay in the parent on a per-frame relay that routes
      each frame by (app_id, partition_index) — correct for everything,
      just not the fast path.
    - aggregates the workers' replica state into ONE beacon (the meta
      still sees one node) and replays cached open-replica state into a
      restarted worker so a crashed group re-serves without waiting for
      the meta's next proposal round.

  worker (ReplicaStub with a group spec, server/__main__.py
  ``--group-worker``)
    - a full replica stub on an ephemeral localhost port: engine, plog,
      PacificA, throttling — nothing shared with its sibling groups
    - identifies as the PUBLIC address (replica naming / primary identity
      must match what the meta assigned), never beacons itself
    - adopts handed-off client sockets from the parent's control channel

Consistency is unchanged: group boundaries follow the existing
per-partition serialization (one writer per partition, partition-hash
sanity check, never-ack-before-durable all live in the worker exactly as
they did in the single-process stub).
"""

import json
import os
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time

from ..meta import messages as mm
from ..rpc import codec
from ..rpc.transport import (ConnectionPool, ERR_NETWORK_FAILURE,
                             RpcConnection, RpcError, RpcHeader, _send_frame)
from ..runtime import lockrank
from ..runtime.perf_counters import counters
from ..runtime.tasking import spawn_thread

RPC_GROUP_STATE = "RPC_GROUP_STATE"  # worker -> parent beacon fragment


def group_of(app_id: int, pidx: int, n_groups: int) -> int:
    """Partition -> group executor map. pidx % n keeps it consistent with
    the client's hash % partition_count routing: consecutive partitions
    land on different groups, so hash-uniform traffic spreads evenly."""
    return pidx % max(1, n_groups)


# frames routable by the RPC header's (app_id, partition_index); bodies of
# these lifecycle codes carry the partition too, for senders that predate
# header routing
_BODY_ROUTED = None


def _body_routed():
    global _BODY_ROUTED
    if _BODY_ROUTED is None:
        from ..meta.meta_server import (RPC_BULK_LOAD, RPC_CLOSE_REPLICA,
                                        RPC_COLD_BACKUP, RPC_OPEN_REPLICA,
                                        RPC_REPLICA_STATE)
        from .replica_stub import RPC_LEARN, RPC_PREPARE

        _BODY_ROUTED = {
            RPC_OPEN_REPLICA: mm.OpenReplicaRequest,
            RPC_CLOSE_REPLICA: mm.CloseReplicaRequest,
            RPC_REPLICA_STATE: mm.ReplicaStateRequest,
            RPC_COLD_BACKUP: mm.OpenReplicaRequest,
            RPC_BULK_LOAD: mm.OpenReplicaRequest,
            RPC_PREPARE: mm.PrepareRequest,
            RPC_LEARN: mm.LearnRequest,
        }
    return _BODY_ROUTED


def _merge_command_outputs(parts):
    """Merge per-group remote-command outputs into ONE response the
    caller can still parse. JSON-dict outputs (perf-counters*,
    replica-disk, collector scrapes) merge structurally — numeric values
    sum across groups, percentile dicts take the per-quantile max (the
    collector's own merge rule) — because a '\\n'.join of two dicts is
    not JSON and would silently blind every scraper. JSON lists concat;
    anything non-JSON joins line-wise (flush-log, describe, ...)."""
    parts = [p for p in parts if p]
    if len(parts) <= 1:
        return parts[0] if parts else ""
    try:
        docs = [json.loads(p) for p in parts]
    except ValueError:
        return "\n".join(parts)
    if all(isinstance(d, list) for d in docs):
        return json.dumps([x for d in docs for x in d])
    if not all(isinstance(d, dict) for d in docs):
        return "\n".join(parts)
    merged = {}
    for d in docs:
        for k, v in d.items():
            cur = merged.get(k)
            if cur is None:
                merged[k] = v
            elif isinstance(cur, (int, float)) \
                    and isinstance(v, (int, float)):
                merged[k] = cur + v
            elif isinstance(cur, dict) and isinstance(v, dict):
                merged[k] = {q: max(cur.get(q, 0), v.get(q, 0))
                             for q in set(cur) | set(v)}
            # else: first group's value wins (strings, mixed shapes)
    return json.dumps(merged)


class _Worker:
    """One spawned group executor process + its control channel."""

    def __init__(self, g: int):
        self.g = g
        self.proc = None
        self.port = 0          # worker's real localhost RPC port
        self.ctrl = None       # unix-socket control conn (handoffs ride it)
        self.ctrl_lock = lockrank.named_lock("serve_groups.ctrl")
        self.ctrl_ok = True    # False after a failed/timed-out handoff:
        # the channel may be desynced, so no further handoffs — relay
        # still serves everything; restart_group builds a fresh channel
        self.alive = False

    def close(self):
        self.alive = False
        if self.ctrl is not None:
            try:
                self.ctrl.close()
            except OSError:
                pass
            self.ctrl = None


class GroupedReplicaNode:
    """Drop-in for ReplicaStub at the node level when serving is split
    across partition-group executors. Exposes the surface the service
    container and the onebox harnesses use: address, start/stop, plus
    kill_group/restart_group for chaos tests."""

    def __init__(self, root: str, meta_addrs, host: str = "127.0.0.1",
                 port: int = 0, groups: int = 2, backend: str = "cpu",
                 compression: str = "none", sharded_compaction: bool = False,
                 remote_clusters: dict = None, cluster_id: int = 1,
                 spawn_timeout: float = 120.0):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.meta_addrs = list(meta_addrs)
        self.groups = max(1, int(groups))
        self.spawn_timeout = spawn_timeout
        self._spec_base = {
            "root": root, "metas": self.meta_addrs, "backend": backend,
            "compression": compression,
            "sharded_compaction": sharded_compaction,
            "remote_clusters": remote_clusters or {},
            "cluster_id": cluster_id, "group_count": self.groups,
        }
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(128)
        self.address = (f"{self._listener.getsockname()[0]}:"
                        f"{self._listener.getsockname()[1]}")
        self._ctrl_dir = tempfile.mkdtemp(prefix="pegasus_grp_")
        self._workers = [_Worker(g) for g in range(self.groups)]
        self._lock = lockrank.named_lock("serve_groups.node")
        # (app_id, pidx) -> open-replica body bytes
        self._open_cache = {}     #: guarded_by self._lock
        self.pool = ConnectionPool()   # beacons to the metas
        self._stop = threading.Event()
        self._threads = []
        self._c_handoff = counters.rate("serve.group.handoff_count")
        self._c_relay = counters.rate("serve.group.relay_count")
        self._c_active = counters.number("serve.group.active")
        self._c_restart = counters.rate("serve.group.restart_count")
        self._c_down = counters.rate("serve.group.down_error_count")
        # reporter-route compatibility with ReplicaStub (empty: the
        # replicas live in the workers; /replica/info on a grouped node
        # reports per-group state via query_replica_info instead)
        self._replicas = {}

    # ------------------------------------------------------------ lifecycle

    def start(self, beacon_interval: float = 1.0,
              maintenance_interval: float = 60.0) -> "GroupedReplicaNode":
        self._beacon_interval = beacon_interval
        threads = [spawn_thread(self._spawn_checked, g, start=False)
                   for g in range(self.groups)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dead = [w.g for w in self._workers if not w.alive]
        if dead:
            self.stop()
            raise RuntimeError(f"group executors failed to start: {dead}")
        self._c_active.set(sum(w.alive for w in self._workers))
        for target in (self._accept_loop, self._beacon_loop):
            self._threads.append(spawn_thread(target, daemon=True))
        self.send_beacon()
        from ..runtime.metric_history import HISTORY

        HISTORY.start()   # the router's own serve.group.* series
        return self

    def _spawn_checked(self, g: int):
        try:
            self._spawn(g)
        except Exception as e:  # noqa: BLE001 - start() reports the group
            print(f"[serve-groups] group {g} spawn failed: {e!r}", flush=True)

    def _spawn(self, g: int):
        w = self._workers[g]
        ctrl_path = os.path.join(self._ctrl_dir, f"g{g}.sock")
        try:
            os.unlink(ctrl_path)
        except OSError:
            pass
        spec = dict(self._spec_base, group_index=g,
                    public_address=self.address, control_path=ctrl_path)
        spec_path = os.path.join(self._ctrl_dir, f"g{g}.spec.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f)
        import pegasus_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(pegasus_tpu.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env["PEGASUS_GROUP_WORKER"] = "1"   # the conftest reaper's marker
        env.pop("PEGASUS_SERVE_GROUPS", None)  # a worker must never nest
        proc = subprocess.Popen(
            [sys.executable, "-m", "pegasus_tpu.server", "--group-worker",
             spec_path],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            stdin=subprocess.DEVNULL, text=True, env=env)
        ready = threading.Event()
        port_box = [0]

        def drain():
            for line in proc.stdout:
                line = line.rstrip("\n")
                if line.startswith("GROUP_READY "):
                    port_box[0] = int(line.split()[1])
                    ready.set()
                else:
                    print(f"[group{g}] {line}", flush=True)
            ready.set()  # EOF: unblock the waiter (alive check fails below)

        spawn_thread(drain, daemon=True)
        if not ready.wait(self.spawn_timeout) or not port_box[0]:
            proc.kill()
            raise RuntimeError(f"group {g} produced no GROUP_READY "
                               f"within {self.spawn_timeout:.0f}s")
        ctrl = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        ctrl.connect(ctrl_path)
        w.proc, w.port, w.ctrl, w.alive = proc, port_box[0], ctrl, True
        w.ctrl_ok = True

    def stop(self):
        if not self._stop.is_set():
            # once only: a chaos kill + teardown both stop the node, and
            # a double drop of the refcounted sampler ref would stop it
            # out from under every other live stub in this process
            from ..runtime.metric_history import HISTORY

            HISTORY.stop()
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        for w in self._workers:
            w.close()   # control-channel EOF = the worker's exit signal
        for w in self._workers:
            if w.proc is not None:
                try:
                    w.proc.terminate()
                    w.proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired):
                    try:
                        w.proc.kill()
                        w.proc.wait(timeout=5)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
        self.pool.close()
        import shutil

        shutil.rmtree(self._ctrl_dir, ignore_errors=True)

    # ----------------------------------------------------------- chaos API

    def kill_group(self, g: int):
        """Hard-kill one group executor (chaos: a wedged/crashed group)."""
        w = self._workers[g]
        port = w.port
        w.close()
        if w.proc is not None:
            try:
                w.proc.kill()
                w.proc.wait(timeout=5)
            except (OSError, subprocess.TimeoutExpired):
                pass
        if port:
            self.pool.invalidate(("127.0.0.1", port))
        self._c_active.set(sum(x.alive for x in self._workers))

    def group_alive(self, g: int, probe: bool = True) -> bool:
        """Is group executor `g` serving? With probe=True (default) the
        worker must also ANSWER an RPC_GROUP_STATE round trip — the
        chaos harness's recovery check after restart_group: a respawned
        process that never reached serving must not count as healed."""
        w = self._workers[g]
        if not w.alive:
            return False
        if not probe:
            return True
        try:
            self._upstream(g).call(RPC_GROUP_STATE, b"", timeout=2.0)
            return True
        except (RpcError, OSError, ConnectionError):
            return False

    def restart_group(self, g: int):
        """Respawn a dead group and replay its cached open-replica state
        so it re-serves immediately (decree state recovers from the
        shared-on-disk plog + engine; the meta's next proposal round
        would eventually do the same, this just doesn't wait for it)."""
        self._spawn(g)
        self._c_restart.increment()
        self._c_active.set(sum(x.alive for x in self._workers))
        from ..runtime import events

        events.emit("serve_group.worker_restart", severity="warn", group=g)
        with self._lock:
            cached = [(k, v) for k, v in self._open_cache.items()
                      if group_of(k[0], k[1], self.groups) == g]
        from ..meta.meta_server import RPC_OPEN_REPLICA

        for (app_id, pidx), body in cached:
            try:
                self._upstream(g).call(RPC_OPEN_REPLICA, body, app_id=app_id,
                                       partition_index=pidx, timeout=30.0)
            except (RpcError, OSError, ConnectionError) as e:
                print(f"[serve-groups] replay {app_id}.{pidx} -> group {g} "
                      f"failed: {e!r}", flush=True)

    # ------------------------------------------------------------- routing

    def _upstream(self, g: int) -> RpcConnection:
        """Parent->worker connection, cached in the node's ConnectionPool
        (reconnect-on-failure semantics come with it; a restarted worker
        gets a fresh port and therefore a fresh pool entry)."""
        w = self._workers[g]
        if not w.alive:
            raise ConnectionError(f"group {g} down")
        return self.pool.get(("127.0.0.1", w.port))

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            spawn_thread(self._router_conn, conn, daemon=True)

    @staticmethod
    def _read_first_frame(conn):
        """-> (RpcHeader, buffered bytes incl. the frame and any extra
        already-received bytes), or (None, b"") at EOF."""
        buf = bytearray()
        while True:
            if len(buf) >= 8:
                (plen,) = struct.unpack_from("<I", buf, 0)
                if len(buf) >= 4 + plen:
                    (hlen,) = struct.unpack_from("<I", buf, 4)
                    header = codec.decode(RpcHeader, bytes(buf[8:8 + hlen]))
                    return header, buf
            chunk = conn.recv(1 << 16)
            if not chunk:
                return None, b""
            buf += chunk

    def _handoff(self, w: _Worker, conn, buffered: bytes) -> bool:
        """Pass the connected socket + its already-read bytes to the
        worker over the control channel (SCM_RIGHTS). -> True on success
        (the parent must then close its fd copy and forget the conn)."""
        payload = struct.pack("<I", len(buffered)) + bytes(buffered)
        try:
            with w.ctrl_lock:
                # local ref: kill_group() nulls w.ctrl concurrently (it
                # does not take ctrl_lock — closing must not queue behind
                # a wedged handoff), so every touch below goes through
                # `ctrl`, and a close mid-handoff surfaces as OSError
                ctrl = w.ctrl
                if not w.ctrl_ok or ctrl is None:
                    return False
                # send_fds is ONE sendmsg: the fd rides its ancillary data,
                # but a large first frame can exceed the unix-socket buffer
                # and return a SHORT write — push the rest with sendall or
                # both ends wedge (worker waiting for bytes, parent for ack)
                ctrl.settimeout(10.0)  # a wedged worker must not pin
                # ctrl_lock forever (every later handoff would queue on it)
                try:
                    sent = socket.send_fds(ctrl, [payload],
                                           [conn.fileno()])
                    if sent < len(payload):
                        ctrl.sendall(payload[sent:])
                    # 1-byte ack serializes fd+payload pairs on the stream
                    if ctrl.recv(1) != b"A":
                        raise ConnectionError("handoff not acked")
                finally:
                    try:
                        ctrl.settimeout(None)
                    except OSError:
                        pass   # closed mid-handoff (kill_group)
            return True
        except (OSError, ConnectionError) as e:
            # the channel may be desynced mid-message: stop handing off to
            # this group but KEEP it alive — relay still serves it, and a
            # transient send failure must not take the whole group down
            w.ctrl_ok = False
            from ..runtime import events

            events.emit("serve_group.handoff_degraded", severity="error",
                        group=w.g, error=repr(e)[:200])
            print(f"[serve-groups] group {w.g} handoff channel degraded "
                  f"({e!r}); serving via relay until restart", flush=True)
            return False

    def _route_frame(self, header, body):
        """-> group index, or None for node-level codes."""
        if header.app_id > 0 or header.partition_index > 0:
            return group_of(header.app_id, header.partition_index,
                            self.groups)
        req_cls = _body_routed().get(header.code)
        if req_cls is not None:
            try:
                req = codec.decode(req_cls, body)
                return group_of(req.app_id, req.pidx, self.groups)
            except codec.CodecError:
                return None
        if header.code == RPC_GROUP_STATE:
            return 0
        return None   # node-level: fan out

    def _router_conn(self, conn):
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            header, buffered = self._read_first_frame(conn)
        except (OSError, codec.CodecError):
            header = None
        if header is None:
            try:
                conn.close()
            except OSError:
                pass
            return
        # fast path: a sharded connection carries ONE partition's frames —
        # hand the socket to the owning group and get out of the way
        if header.sharded:
            g = self._route_frame(header, b"")
            if g is not None:
                w = self._workers[g]
                if w.alive and self._handoff(w, conn, buffered):
                    self._c_handoff.increment()
                    try:
                        conn.close()   # worker owns the duplicated fd now
                    except OSError:
                        pass
                    return
        # relay path: serve the connection here, routing frame by frame
        self._relay_conn(conn, bytes(buffered))

    def _relay_conn(self, conn, initial: bytes):
        from ..rpc.transport import make_frame_reader

        wlock = threading.Lock()
        try:
            reader = make_frame_reader(conn, initial)
            while True:
                for header, body in reader.wave():
                    try:
                        self._relay_frame(conn, wlock, header, body)
                    except (ConnectionError, OSError):
                        raise
                    except Exception as e:  # noqa: BLE001 - a router bug
                        # must surface as an error RESPONSE, not a dead
                        # connection the client can only time out on
                        err = RpcHeader(seq=header.seq, code=header.code,
                                        is_response=True,
                                        error=ERR_NETWORK_FAILURE,
                                        error_text=f"router error: {e!r}")
                        _send_frame(conn, err, b"", lock=wlock)
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _relay_frame(self, conn, wlock, header, body):
        from ..meta.meta_server import RPC_CLOSE_REPLICA, RPC_OPEN_REPLICA

        self._c_relay.increment()
        g = self._route_frame(header, body)
        resp = RpcHeader(seq=header.seq, code=header.code, is_response=True)
        out = b""
        if g is None:
            resp, out = self._fanout(header, body, resp)
        else:
            # lifecycle cache: a restarted group replays from here
            if header.code == RPC_OPEN_REPLICA:
                try:
                    req = codec.decode(mm.OpenReplicaRequest, body)
                    with self._lock:
                        self._open_cache[(req.app_id, req.pidx)] = body
                except codec.CodecError:
                    pass
            elif header.code == RPC_CLOSE_REPLICA:
                try:
                    req = codec.decode(mm.CloseReplicaRequest, body)
                    with self._lock:
                        self._open_cache.pop((req.app_id, req.pidx), None)
                except codec.CodecError:
                    pass
            try:
                rh, out = self._upstream(g).call(
                    header.code, body, app_id=header.app_id,
                    partition_index=header.partition_index,
                    partition_hash=header.partition_hash, timeout=60.0)
            except RpcError as e:
                resp.error, resp.error_text = e.err, e.text
                if e.err == ERR_NETWORK_FAILURE:
                    self._c_down.increment()
            except (OSError, ConnectionError) as e:
                resp.error = ERR_NETWORK_FAILURE
                resp.error_text = f"group {g} down: {e}"
                self._c_down.increment()
        try:
            _send_frame(conn, resp, out, lock=wlock)
        except (ConnectionError, OSError):
            pass

    def _fanout(self, header, body, resp):
        """Node-level codes hit every live group; responses merge."""
        from ..meta.meta_server import RPC_QUERY_REPLICA_INFO
        from ..runtime.remote_command import (RemoteCommandResponse)
        from .replica_stub import RPC_REMOTE_COMMAND

        results, last_err = [], None
        for g in range(self.groups):
            try:
                results.append(self._upstream(g).call(header.code, body,
                                                      timeout=30.0))
            except (RpcError, OSError, ConnectionError) as e:
                last_err = e
        if not results:
            resp.error = ERR_NETWORK_FAILURE
            resp.error_text = f"no live group: {last_err}"
            return resp, b""
        if header.code == RPC_QUERY_REPLICA_INFO:
            merged = []
            for _, rbody in results:
                merged.extend(codec.decode(mm.QueryReplicaInfoResponse,
                                           rbody).replicas)
            return resp, codec.encode(
                mm.QueryReplicaInfoResponse(replicas=merged))
        if header.code == RPC_REMOTE_COMMAND:
            parts = [codec.decode(RemoteCommandResponse, rbody).output
                     for _, rbody in results]
            return resp, codec.encode(RemoteCommandResponse(
                _merge_command_outputs(parts)))
        return resp, results[0][1]

    # ------------------------------------------------------------- beacons

    def _beacon_loop(self):
        while not self._stop.wait(self._beacon_interval):
            try:
                self.send_beacon()
            except Exception as e:  # a dead beacon loop = node declared dead
                print(f"[serve-groups beacon] {self.address}: {e!r}",
                      flush=True)

    def send_beacon(self):
        """ONE beacon for the whole node: merge every live worker's
        replica/dup state (RPC_GROUP_STATE) under the public address."""
        from ..meta.meta_server import RPC_FD_BEACON

        alive, progress, states = [], [], []
        for g in range(self.groups):
            if not self._workers[g].alive:
                continue
            try:
                _, rbody = self._upstream(g).call(RPC_GROUP_STATE, b"",
                                                  timeout=2.0)
                st = json.loads(rbody.decode("utf-8"))
                alive.extend(st.get("alive", []))
                progress.extend(st.get("dup_progress", []))
                states.extend(st.get("states", []))
            except (RpcError, OSError, ConnectionError, ValueError):
                continue
        body = codec.encode(mm.BeaconRequest(
            node=self.address, alive_replicas=alive, dup_progress=progress,
            replica_states=states))
        for m in self.meta_addrs:
            host, _, port = m.rpartition(":")
            try:
                self.pool.get((host, int(port))).call(RPC_FD_BEACON, body,
                                                      timeout=2.0)
            except (RpcError, OSError):
                continue
