"""Replica-group controller: the meta server's reconfiguration role,
in-process.

Drives PacificA view changes over a set of Replicas: promote the live
replica with the highest (ballot, last_prepared) — which PacificA's quorum
rule guarantees holds every committed mutation — rebuild dead members as
learners, and re-install views. The kill-test harness (tests/test_kill
pattern, reference src/test/kill_test) runs against exactly this surface;
the standalone meta server drives the same transitions over RPC.
"""

import os
import threading

from ..engine import EngineOptions
from .replica import GroupView, Replica, ReplicaError


class ReplicaGroup:
    def __init__(self, root: str, n: int = 3, app_id: int = 1, pidx: int = 0,
                 options_factory=None, quorum: int = 2):
        self.root = root
        self.names = [f"r{i}" for i in range(n)]
        self.app_id = app_id
        self.pidx = pidx
        self.quorum = quorum
        self.options_factory = options_factory or (lambda: EngineOptions(backend="cpu"))
        self._lock = threading.RLock()
        self.alive = {}     # name -> Replica
        self.ballot = 0
        self.primary = None
        for name in self.names:
            self.alive[name] = self._open(name)
        self.elect()

    def _open(self, name: str) -> Replica:
        return Replica(name, os.path.join(self.root, name), self.app_id,
                       self.pidx, self.options_factory(), peers=self._peer,
                       quorum=self.quorum)

    def _peer(self, name: str):
        r = self.alive.get(name)
        if r is None:
            raise ConnectionError(name)
        return r

    # ------------------------------------------------------------- control

    def elect(self) -> Replica:
        """Install a new view: best live replica becomes primary."""
        with self._lock:
            if not self.alive:
                raise ReplicaError("no live replicas")
            best = max(self.alive.values(),
                       key=lambda r: (r.ballot, r.last_prepared))
            self.ballot = max(self.ballot, best.ballot) + 1
            self.primary = best.name
            secondaries = [n for n in self.alive if n != best.name]
            view = GroupView(self.ballot, best.name, secondaries)
            best.assume_view(view)
            for n in secondaries:
                self.alive[n].assume_view(view)
            return best

    def kill(self, name: str) -> None:
        """Hard-kill: drop the object without flushing (data loss beyond the
        log is the point of the test)."""
        with self._lock:
            r = self.alive.pop(name, None)
            if r:
                r.plog.close()
            if name == self.primary and self.alive:
                self.elect()

    def restart(self, name: str) -> Replica:
        """Reopen from disk; rejoin as learner unless it wins the election
        (e.g. after a full-group crash)."""
        with self._lock:
            r = self._open(name)
            self.alive[name] = r
            if self.primary in self.alive and self.primary != name:
                r.learn_from(self.alive[self.primary])
                self.alive[self.primary].view.secondaries.append(name)
                r.assume_view(GroupView(self.ballot, self.primary,
                                        self.alive[self.primary].view.secondaries))
            else:
                self.elect()
            return r

    def primary_replica(self) -> Replica:
        return self.alive[self.primary]

    def write(self, code: str, req, now=None):
        return self.primary_replica().client_write(code, req, now=now)

    def read(self, key: bytes, now=None):
        return self.primary_replica().server.on_get(key, now=now)

    def close(self):
        for r in self.alive.values():
            r.close()
