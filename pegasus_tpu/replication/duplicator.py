"""Cross-cluster duplication: ship committed mutations to a remote cluster.

Mirror of pegasus_mutation_duplicator + the rDSN duplication framework
(SURVEY.md §2.4 'Duplication framework'; reference
src/server/pegasus_mutation_duplicator.{h,cpp}): a hook on the replica's
commit path enqueues every mutation; a shipper thread replays them to the
remote cluster as RPC_RRDB_RRDB_DUPLICATE writes carrying the origin
timestamp + cluster id. The remote applies them through its own PacificA
(so duplicates are themselves replicated), with last-writer-wins conflict
resolution via the value-schema timetag (verify_timetag). Shipping is
in-order overall, which subsumes the reference's per-hash FIFO guarantee.
"""

import threading

from ..base import key_schema
from ..engine.replica_service import WRITE_CODES
from ..engine.server_impl import RPC_DUPLICATE
from ..rpc import codec
from ..rpc import messages as msg
from ..rpc.transport import ConnectionPool, RpcError
from .mutation_log import LogMutation


class MutationDuplicator:
    """Attach with `replica.commit_hooks.append(dup.on_commit)`."""

    def __init__(self, remote_resolver, cluster_id: int = 1,
                 fail_mode: str = "slow"):
        """remote_resolver: client resolver for the remote table;
        fail_mode: 'slow' blocks/retries (default), 'skip' drops on error
        (reference dup fail-mode knob)."""
        self.resolver = remote_resolver
        self.cluster_id = cluster_id
        self.fail_mode = fail_mode
        self.pool = ConnectionPool()
        self._queue = []
        self._cv = threading.Condition()
        self._stop = False
        self._inflight = False
        self.shipped = 0
        self.skipped = 0
        self.last_shipped_decree = 0
        self._thread = threading.Thread(target=self._ship_loop, daemon=True)
        self._thread.start()

    # ----------------------------------------------------------------- hook

    def on_commit(self, m: LogMutation) -> None:
        with self._cv:
            self._queue.append(m)
            self._cv.notify()

    # ----------------------------------------------------------------- ship

    def _ship_loop(self):
        while True:
            with self._cv:
                self._inflight = False
                self._cv.notify_all()
                while not self._queue and not self._stop:
                    self._cv.wait(0.2)
                if self._stop and not self._queue:
                    return
                m = self._queue.pop(0)
                self._inflight = True
            try:
                self._ship_one(m)
            except Exception as e:  # never let the shipper thread die
                self.skipped += 1
                print(f"[duplicator] dropped decree {m.decree}: {e!r}")

    def _ship_one(self, m: LogMutation) -> None:
        import time

        for code, body in zip(m.codes, m.bodies):
            if code == RPC_DUPLICATE:
                continue  # never re-duplicate a duplicate (loop guard)
            try:
                key = _routing_key(code, body)
            except (ValueError, KeyError):
                # non-duplicable mutation (e.g. bulk-load ingestion commands
                # have no routing key; each cluster loads its own sets)
                self.skipped += 1
                continue
            req = msg.DuplicateRequest(
                timestamp=m.timestamp_us, task_code=code, raw_message=body,
                cluster_id=self.cluster_id, verify_timetag=True)
            attempts = 0
            while not self._stop:
                try:
                    self._send(req, key, refresh=attempts > 0)
                    self.shipped += 1
                    break
                except (RpcError, OSError):
                    attempts += 1
                    if self.fail_mode == "skip":
                        self.skipped += 1
                        break
                    # fail_mode='slow': keep the backlog, retry with backoff
                    # (the reference's dup_fail_mode=slow holds the pipeline)
                    time.sleep(min(2.0, 0.05 * attempts))
        self.last_shipped_decree = max(self.last_shipped_decree, m.decree)

    def _send(self, req: msg.DuplicateRequest, key: bytes,
              refresh: bool = False) -> None:
        if refresh:
            self.resolver.refresh()
        h = key_schema.key_hash(key)
        pidx = h % self.resolver.partition_count
        addr = self.resolver.resolve(pidx)
        try:
            conn = self.pool.get(addr)
            conn.call(RPC_DUPLICATE, codec.encode(req),
                      app_id=self.resolver.app_id, partition_index=pidx,
                      partition_hash=h, timeout=10.0)
        except (RpcError, OSError):
            self.pool.invalidate(addr)
            raise

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait until the backlog drains AND the in-flight mutation (if any)
        finished shipping (tests / graceful shutdown)."""
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._cv:
                if not self._queue and not self._inflight:
                    return True
            time.sleep(0.01)
        return False

    def stop(self):
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=5)
        self.pool.close()


def _routing_key(code: str, body: bytes) -> bytes:
    """The hash-carrying key of a mutation (get_hash_from_request role,
    reference pegasus_mutation_duplicator.cpp)."""
    req_cls, _ = WRITE_CODES[code]
    req = codec.decode(req_cls, body)
    if hasattr(req, "key"):
        return req.key
    if hasattr(req, "hash_key"):
        return key_schema.generate_key(req.hash_key, b"")
    raise ValueError(f"cannot route duplicate of {code}")
