"""Cross-cluster duplication: ship committed mutations to a remote cluster.

Mirror of pegasus_mutation_duplicator + the rDSN duplication framework
(SURVEY.md §2.4 'Duplication framework'; reference
src/server/pegasus_mutation_duplicator.{h,cpp}): a hook on the replica's
commit path enqueues every mutation; a shipper thread replays them to the
remote cluster as RPC_RRDB_RRDB_DUPLICATE writes carrying the origin
timestamp + cluster id. The remote applies them through its own PacificA
(so duplicates are themselves replicated), with last-writer-wins conflict
resolution via the value-schema timetag (verify_timetag). Shipping is
in-order overall, which subsumes the reference's per-hash FIFO guarantee.
"""

import json
import os
import threading

from ..base import key_schema
from ..engine.replica_service import WRITE_CODES
from ..engine.server_impl import RPC_DUPLICATE
from ..rpc import codec
from ..rpc import messages as msg
from ..rpc.transport import ConnectionPool, RpcError
from ..runtime.tasking import spawn_thread
from .mutation_log import LogMutation


class MutationDuplicator:
    """Attach with `replica.commit_hooks.append(dup.on_commit)`."""

    def __init__(self, remote_resolver, cluster_id: int = 1,
                 fail_mode: str = "slow", dupid: int = 0,
                 progress_dir: str = None, confirmed_floor: int = 0,
                 paused: bool = False):
        """remote_resolver: client resolver for the remote table;
        fail_mode: 'slow' blocks/retries (default), 'skip' drops on error
        (reference dup fail-mode knob); progress_dir: local persistence of
        the confirmed decree; confirmed_floor: the meta-held confirmed
        decree for this partition (beacon-reported; survives failover the
        way the reference's meta duplication_info.progress does) — shipping
        starts past max(local, floor). Create with paused=True and unpause
        only after catch_up(): otherwise a live hook mutation can ship
        first and advance the confirmed decree past the unshipped
        backlog, which would then be skipped forever."""
        self.resolver = remote_resolver
        self.cluster_id = cluster_id
        self.fail_mode = fail_mode
        self.dupid = dupid
        self.pool = ConnectionPool()
        self._queue = []
        self._cv = threading.Condition()
        self._stop = False
        self._paused = paused
        self._inflight = False
        self.shipped = 0
        self.skipped = 0
        self._progress_path = (os.path.join(progress_dir, f"dup_{dupid}.json")
                               if progress_dir else None)
        self.last_shipped_decree = max(self._load_progress(), confirmed_floor)
        self._saved_decree = self.last_shipped_decree
        self._saved_at = 0.0
        # one long-lived traced job per duplicator (ISSUE 16): each
        # shipped window notes a hop, stop() closes it — the timeline is
        # the ship cadence between this cluster and the remote
        from ..runtime.job_trace import JOB_TRACER

        self._trace_job = JOB_TRACER.begin("duplicate", dupid=dupid,
                                           cluster=cluster_id)
        self._thread = spawn_thread(self._ship_loop, daemon=True)

    # ------------------------------------------------------------- progress

    def _load_progress(self) -> int:
        if self._progress_path and os.path.exists(self._progress_path):
            try:
                with open(self._progress_path) as f:
                    return int(json.load(f)["confirmed_decree"])
            except (OSError, ValueError, KeyError):
                pass
        return 0

    _SAVE_EVERY_DECREES = 64
    _SAVE_EVERY_SECONDS = 1.0

    def _save_progress(self, force: bool = False) -> None:
        """Batched persistence: the file is a restart HINT (catch_up + the
        meta confirmed floor cover a stale value, shipping is at-least-
        once), so a write+rename per confirmed decree buys nothing."""
        import time

        if not self._progress_path:
            return
        if not force:
            due = (self.last_shipped_decree - self._saved_decree
                   >= self._SAVE_EVERY_DECREES
                   or time.monotonic() - self._saved_at
                   >= self._SAVE_EVERY_SECONDS)
            if not due:
                return
        tmp = self._progress_path + ".tmp"
        os.makedirs(os.path.dirname(self._progress_path), exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({"dupid": self.dupid,
                       "confirmed_decree": self.last_shipped_decree}, f)
        os.replace(tmp, self._progress_path)
        self._saved_decree = self.last_shipped_decree
        self._saved_at = time.monotonic()

    def catch_up(self, plog) -> int:
        """Backfill the ship queue from the plog past the confirmed decree —
        how a fresh duplicator (dup add, restart, failover promotion) ships
        history it never saw via the commit hook. Shipping is at-least-once:
        overlap with live hook traffic resolves at the remote via the
        timetag LWW (verify_timetag). Returns the number backfilled."""
        backlog = [m for m in plog.replay(self.last_shipped_decree)]
        with self._cv:
            self._queue[:0] = backlog
            self._cv.notify()
        return len(backlog)

    # ----------------------------------------------------------------- hook

    def on_commit(self, m: LogMutation) -> None:
        with self._cv:
            self._queue.append(m)
            self._cv.notify()

    def set_paused(self, paused: bool) -> None:
        """Pause = stop shipping but KEEP queueing (the backlog survives;
        the plog + persisted progress cover a process restart while
        paused)."""
        with self._cv:
            self._paused = paused
            self._cv.notify()

    # ----------------------------------------------------------------- ship

    _SHIP_BATCH = 32   # queued mutations shipped per pipelined wave

    def _ship_loop(self):
        while True:
            with self._cv:
                self._inflight = False
                self._cv.notify_all()
                while (not self._queue or self._paused) and not self._stop:
                    self._cv.wait(0.2)
                if self._stop and (not self._queue or self._paused):
                    return
                batch = self._queue[:self._SHIP_BATCH]
                del self._queue[:len(batch)]
                self._inflight = True
            # batched fast path: a backlog (catch-up, paused burst, slow
            # remote) ships as ONE pipelined call_many wave per (node,
            # partition) instead of a round trip per request. Any failure
            # falls back to the per-mutation retry/skip policy below —
            # shipping is at-least-once and the remote's timetag LWW
            # resolves the overlap.
            shipped_batch = False
            if len(batch) > 1:
                try:
                    shipped_batch = self._ship_window(batch)
                except Exception:  # noqa: BLE001 - wave failed: retry singly
                    shipped_batch = False
            if shipped_batch:
                self._save_progress()
                continue
            for m in batch:
                try:
                    if self._ship_one(m):
                        self._save_progress()
                except Exception as e:  # never let the shipper thread die
                    self.skipped += 1
                    print(f"[duplicator] dropped decree {m.decree}: {e!r}")

    def _ship_window(self, ms) -> bool:
        """Ship a window of mutations as batched per-partition waves.
        -> True only when EVERY request landed (the window's decrees are
        then confirmed in order). Per-partition request order is
        preserved, which keeps the per-hash FIFO guarantee; cross-
        partition order is already unordered at the remote."""
        groups = {}   # (addr, pidx) -> ordered call list
        n_skipped = 0  # counted only once the WHOLE window lands — a
        # failed wave reruns through _ship_one, which does its own count
        for m in ms:
            if m.decree <= self.last_shipped_decree:
                continue
            for code, body in zip(m.codes, m.bodies):
                if code == RPC_DUPLICATE:
                    continue   # never re-duplicate a duplicate (loop guard)
                try:
                    key = _routing_key(code, body)
                except (ValueError, KeyError):
                    n_skipped += 1   # non-duplicable (e.g. bulk load)
                    continue
                req = msg.DuplicateRequest(
                    timestamp=m.timestamp_us, task_code=code,
                    raw_message=body, cluster_id=self.cluster_id,
                    verify_timetag=True)
                h = key_schema.key_hash(key)
                pidx = h % self.resolver.partition_count
                addr = tuple(self.resolver.resolve(pidx))
                groups.setdefault((addr, pidx), []).append(
                    (RPC_DUPLICATE, codec.encode(req),
                     self.resolver.app_id, pidx, h))
        pends = []
        for (addr, pidx), calls in groups.items():
            conn = self.pool.get(addr, shard=pidx)
            pends.append((conn, calls, conn.call_many_send(calls)))
        n = 0
        for conn, calls, handle in pends:
            conn.call_many_collect(handle, calls, 10.0)
            n += len(calls)
        self.shipped += n
        self.skipped += n_skipped
        self.last_shipped_decree = max(self.last_shipped_decree,
                                       ms[-1].decree)
        from ..runtime.job_trace import JOB_TRACER

        JOB_TRACER.note("dup.ship_window", job_id=self._trace_job,
                        requests=n, skipped=n_skipped,
                        decree=self.last_shipped_decree)
        return True

    def _ship_one(self, m: LogMutation) -> bool:
        """-> True when the decree is confirmed (shipped, or skipped by
        policy). stop() mid-retry returns False: the decree was NOT
        delivered and must not be recorded as confirmed."""
        import time

        if m.decree <= self.last_shipped_decree:
            return True  # catch_up/live-hook overlap: already confirmed
        for code, body in zip(m.codes, m.bodies):
            if code == RPC_DUPLICATE:
                continue  # never re-duplicate a duplicate (loop guard)
            try:
                key = _routing_key(code, body)
            except (ValueError, KeyError):
                # non-duplicable mutation (e.g. bulk-load ingestion commands
                # have no routing key; each cluster loads its own sets)
                self.skipped += 1
                continue
            req = msg.DuplicateRequest(
                timestamp=m.timestamp_us, task_code=code, raw_message=body,
                cluster_id=self.cluster_id, verify_timetag=True)
            attempts = 0
            while True:
                if self._stop:
                    return False  # interrupted mid-retry: NOT confirmed
                try:
                    self._send(req, key, refresh=attempts > 0)
                    self.shipped += 1
                    break
                except (RpcError, OSError):
                    attempts += 1
                    if self.fail_mode == "skip":
                        self.skipped += 1
                        break
                    # fail_mode='slow': keep the backlog, retry with backoff
                    # (the reference's dup_fail_mode=slow holds the pipeline)
                    time.sleep(min(2.0, 0.05 * attempts))
        self.last_shipped_decree = max(self.last_shipped_decree, m.decree)
        return True

    def _send(self, req: msg.DuplicateRequest, key: bytes,
              refresh: bool = False) -> None:
        if refresh:
            self.resolver.refresh()
        h = key_schema.key_hash(key)
        pidx = h % self.resolver.partition_count
        addr = self.resolver.resolve(pidx)
        try:
            conn = self.pool.get(addr)
            conn.call(RPC_DUPLICATE, codec.encode(req),
                      app_id=self.resolver.app_id, partition_index=pidx,
                      partition_hash=h, timeout=10.0)
        except (RpcError, OSError):
            self.pool.invalidate(addr)
            raise

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait until the backlog drains AND the in-flight mutation (if any)
        finished shipping (tests / graceful shutdown)."""
        import time

        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._cv:
                if not self._queue and not self._inflight:
                    return True
            time.sleep(0.01)
        return False

    def stop(self):
        with self._cv:
            self._stop = True
            self._cv.notify()
        self._thread.join(timeout=5)
        try:
            self._save_progress(force=True)
        except OSError:
            pass
        self.pool.close()
        from ..runtime.job_trace import JOB_TRACER

        JOB_TRACER.finish(self._trace_job, shipped=self.shipped,
                          skipped=self.skipped,
                          decree=self.last_shipped_decree)


def _routing_key(code: str, body: bytes) -> bytes:
    """The hash-carrying key of a mutation (get_hash_from_request role,
    reference pegasus_mutation_duplicator.cpp)."""
    req_cls, _ = WRITE_CODES[code]
    req = codec.decode(req_cls, body)
    if hasattr(req, "key"):
        return req.key
    if hasattr(req, "hash_key"):
        return key_schema.generate_key(req.hash_key, b"")
    raise ValueError(f"cannot route duplicate of {code}")
