"""Duplicator bootstrap: seed a fresh remote cluster by block ship.

Adding duplication to a table whose mutation log no longer reaches back
to decree 0 (plog GC behind durable SSTs is the NORMAL state of a
long-lived table) leaves the remote cluster unseedable by log replay —
the history simply is not in the log any more. This module closes that
gap with the same block-shipping machinery learners use (ISSUE 13):

  1. for every source partition, open a learn session against its
     primary — the same pin/manifest/chunk protocol as a learner
     re-seed (delta-aware and resumable: a re-run of an interrupted
     bootstrap re-fetches only blocks the staging dir is missing);
  2. stage the pinned checkpoint's SST blocks into a bulk-load provider
     layout (``<root>/<app>/<partition_count>/<pidx>/*.sst``);
  3. drive the DESTINATION meta's replicated bulk-load ingest: every
     destination replica ingests the set at the same decree through the
     PacificA write path, so the bootstrap survives destination
     failover.

Run it with the duplication added FROZEN (dup entries hold the source
plog at their confirmed decree), then start the duplication: the log
tail ships the window after the checkpoint, and the PR 8 cross-cluster
decree-anchored digest compare can then prove the whole table
byte-consistent at the duplicator's confirmed decree.
"""

import os

from ..meta import messages as mm
from ..rpc import codec
from ..rpc.transport import ConnectionPool, RpcError
from .learn import RemoteLearnSource, dir_manifest, stage_blocks

# engine-internal files that ride a checkpoint manifest but are not
# ingestable blocks (the provider set is SSTs only)
_NON_BLOCK = {"MANIFEST"}


def ship_partition_blocks(pool: ConnectionPool, primary: str, app_id: int,
                          pidx: int, dest_dir: str) -> dict:
    """Block-ship one source partition's pinned checkpoint SSTs into
    `dest_dir` (delta/resume against whatever is already staged there).
    -> stage_blocks stats + the checkpoint decree."""
    src = RemoteLearnSource(pool, primary, app_id, pidx)
    st = src.prepare_learn_state(have=dir_manifest(dest_dir))
    try:
        st = dict(st, blocks=[e for e in st["blocks"]
                              if e["name"] not in _NON_BLOCK])
        stats = stage_blocks(src, st, dest_dir)
    finally:
        src.finish_learn(st["learn_id"])
    return dict(stats, ckpt_decree=st["ckpt_decree"])


def bootstrap_remote_cluster(src_meta_addrs, dst_meta_addrs, app_name: str,
                             provider_root: str,
                             pool: ConnectionPool = None) -> dict:
    """Seed `app_name` on the destination cluster from the source
    cluster's checkpoints, via block ship + replicated bulk-load ingest.
    Requires the destination table to exist with the same partition
    count (the ingest's hash filter then keeps exactly each partition's
    rows). -> {"partitions", "blocks", "bytes", "skipped", "resumed",
    "ingested_records"}."""
    from ..collector.cluster_doctor import ClusterCaller

    own_pool = pool is None
    pool = pool or ConnectionPool()
    caller = ClusterCaller(src_meta_addrs, pool=pool)
    try:
        state = caller.meta_state()
        if state is None or app_name not in state.get("apps", {}):
            raise RuntimeError(
                f"source cluster state unavailable or no app {app_name!r}")
        app = state["apps"][app_name]
        app_id, pcount = app["app_id"], app["partition_count"]
        totals = {"partitions": 0, "blocks": 0, "bytes": 0, "skipped": 0,
                  "resumed": 0}
        for pc in app["partitions"]:
            if not pc.get("primary"):
                raise RuntimeError(
                    f"partition {pc['pidx']} has no live primary")
            dest = os.path.join(provider_root, app_name, str(pcount),
                                str(pc["pidx"]))
            stats = ship_partition_blocks(pool, pc["primary"], app_id,
                                          pc["pidx"], dest)
            totals["partitions"] += 1
            totals["blocks"] += stats["fetched"]
            totals["bytes"] += stats["bytes"]
            totals["skipped"] += stats["skipped"]
            totals["resumed"] += stats["resumed"]
        from ..engine.bulk_load import write_metadata

        write_metadata(provider_root, app_name, pcount)
        resp = _start_bulk_load(pool, dst_meta_addrs, app_name,
                                provider_root)
        totals["ingested_records"] = resp.ingested_records
        return totals
    finally:
        if own_pool:
            pool.close()


def _start_bulk_load(pool, dst_meta_addrs, app_name: str,
                     provider_root: str):
    """Synchronous bulk-load DDL against the destination meta (first
    reachable leader wins)."""
    from ..meta.meta_server import RPC_CM_START_BULK_LOAD

    last = None
    for meta in dst_meta_addrs:
        host, _, port = meta.rpartition(":")
        try:
            conn = pool.get((host, int(port)))
            _, body = conn.call(
                RPC_CM_START_BULK_LOAD,
                codec.encode(mm.StartBulkLoadRequest(
                    app_name=app_name, provider_root=provider_root)),
                timeout=120.0)
        except (RpcError, OSError) as e:
            last = e
            continue
        resp = codec.decode(mm.StartBulkLoadResponse, body)
        if resp.error:
            raise RuntimeError(f"destination bulk load failed: "
                               f"{resp.error_text}")
        return resp
    raise RuntimeError(f"no destination meta reachable: {last!r}")
