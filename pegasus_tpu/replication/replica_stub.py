"""Replica node: hosts PacificA replicas, beacons to meta, serves clients.

The rDSN replica_stub + pegasus_replication_service_app role (SURVEY.md
§2.4 'Service-app container', §3.1 boot path): one process = one node
address; the meta server opens/closes replicas here (RPC_CONFIG_PROPOSAL_*),
client writes route through the local replica's PacificA 2PC
(replica.client_write), prepares arrive from peer nodes over RPC, learners
pull checkpoint+log-tail state, and a beacon thread keeps the meta lease.
"""

import json
import os
import socket
import threading
import time

from ..engine import EngineOptions
from ..engine.replica_service import ReplicaService, WRITE_CODES
from ..meta import messages as mm
from ..meta.meta_server import (RPC_CLOSE_REPLICA, RPC_FD_BEACON,
                                RPC_OPEN_REPLICA, RPC_REPLICA_STATE)
from ..rpc import codec
from ..rpc.transport import (ConnectionPool, ERR_INVALID_STATE,
                             ERR_OBJECT_NOT_FOUND, RpcError, RpcServer)
from ..runtime.tasking import spawn_thread
from .mutation_log import LogMutation
from .replica import GroupView, PRIMARY, PrepareRejected, Replica, ReplicaError

RPC_PREPARE = "RPC_PREPARE"
RPC_LEARN = "RPC_LEARN"
# block-shipped learn plane (ISSUE 13): manifest-diff handshake, chunked
# pinned-block fetch, log-tail pull, pin release
RPC_LEARN_PREPARE = "RPC_LEARN_PREPARE"
RPC_LEARN_FETCH = "RPC_LEARN_FETCH"
RPC_LEARN_TAIL = "RPC_LEARN_TAIL"
RPC_LEARN_FINISH = "RPC_LEARN_FINISH"
RPC_REMOTE_COMMAND = "RPC_CLI_CLI_CALL"


class _RemotePeer:
    """Peer-node proxy with the Replica peer interface (on_prepare,
    fetch_learn_state) over the RPC transport."""

    def __init__(self, stub: "ReplicaStub", addr: str, app_id: int, pidx: int):
        self.stub = stub
        self.addr = addr
        self.app_id = app_id
        self.pidx = pidx

    def _call(self, code, req):
        host, _, port = self.addr.rpartition(":")
        try:
            # one SHARDED connection per (peer, partition): the peer's
            # partition-group router can hand the whole socket to the
            # owning group executor, and the header carries the route
            conn = self.stub.pool.get((host, int(port)),
                                      shard=("rep", self.app_id, self.pidx))
            _, body = conn.call(code, codec.encode(req), app_id=self.app_id,
                                partition_index=self.pidx, timeout=10.0)
            return body
        except (RpcError, OSError) as e:
            raise ConnectionError(str(e))

    def on_prepare(self, ballot, m: LogMutation, committed_decree: int):
        body = self._call(RPC_PREPARE, mm.PrepareRequest(
            app_id=self.app_id, pidx=self.pidx, ballot=ballot,
            committed_decree=committed_decree, mutation=codec.encode(m)))
        resp = codec.decode(mm.PrepareResponse, body)
        if resp.error:
            raise PrepareRejected(resp.reason, resp.last_prepared)

    def on_prepare_batch(self, ballot, ms, committed_decree: int) -> int:
        """Windowed prepare: the whole decree window rides ONE RPC; the
        peer acks its highest contiguous prepared decree."""
        body = self._call(RPC_PREPARE, mm.PrepareRequest(
            app_id=self.app_id, pidx=self.pidx, ballot=ballot,
            committed_decree=committed_decree,
            mutations=[codec.encode(m) for m in ms]))
        resp = codec.decode(mm.PrepareResponse, body)
        if resp.error:
            raise PrepareRejected(resp.reason, resp.last_prepared)
        return resp.last_prepared

    def on_prepare_windows(self, ballot, windows, committed_decree: int) -> int:
        """Catch-up fast path: every chunked window of the backlog is
        encoded up front and the requests leave in ONE coalesced transport
        send (RpcConnection.call_many — writev-style), then the responses
        are collected in order. -> the peer's final acked decree."""
        host, _, port = self.addr.rpartition(":")
        reqs = [(RPC_PREPARE, codec.encode(mm.PrepareRequest(
            app_id=self.app_id, pidx=self.pidx, ballot=ballot,
            committed_decree=committed_decree,
            mutations=[codec.encode(m) for m in w])),
            self.app_id, self.pidx, 0) for w in windows]
        try:
            conn = self.stub.pool.get((host, int(port)),
                                      shard=("rep", self.app_id, self.pidx))
            results = conn.call_many(reqs, timeout=10.0)
        except (RpcError, OSError) as e:
            raise ConnectionError(str(e))
        last = 0
        for _, body in results:
            resp = codec.decode(mm.PrepareResponse, body)
            if resp.error:
                raise PrepareRejected(resp.reason, resp.last_prepared)
            last = resp.last_prepared
        return last

    def fetch_learn_state(self) -> dict:
        body = self._call(RPC_LEARN, mm.LearnRequest(self.app_id, self.pidx))
        resp = codec.decode(mm.LearnResponse, body)
        if resp.error:
            raise ConnectionError("learn failed")
        return {
            "files": [(f.name, f.data) for f in resp.files],
            "tail": [codec.decode(LogMutation, t) for t in resp.tail],
            "last_committed": resp.last_committed,
            "ballot": resp.ballot,
        }

    # block-shipped learn surface (ISSUE 13): one client implementation
    # (learn.RemoteLearnSource) shared with the duplicator bootstrap —
    # chunk fetches pipeline through call_many waves on the shard's
    # dedicated connection
    def _learn_source(self):
        if getattr(self, "_learn_src", None) is None:
            from .learn import RemoteLearnSource

            self._learn_src = RemoteLearnSource(
                self.stub.pool, self.addr, self.app_id, self.pidx)
        return self._learn_src

    def prepare_learn_state(self, have=None, delta=None) -> dict:
        return self._learn_source().prepare_learn_state(have, delta)

    def fetch_learn_chunks(self, learn_id, reqs) -> list:
        return self._learn_source().fetch_learn_chunks(learn_id, reqs)

    def fetch_learn_tail(self, learn_id) -> dict:
        return self._learn_source().fetch_learn_tail(learn_id)

    def finish_learn(self, learn_id) -> None:
        self._learn_source().finish_learn(learn_id)


class ReplicaStub:
    def __init__(self, root: str, meta_addrs, host: str = "127.0.0.1",
                 port: int = 0, options_factory=None,
                 block_service_provider: str = "local_service",
                 remote_clusters: dict = None, cluster_id: int = 1,
                 group_spec: dict = None):
        self.root = root
        self.meta_addrs = list(meta_addrs)
        # partition-group executor mode (replication/serve_groups.py): this
        # stub is ONE group worker of a grouped serving node — it owns only
        # partitions with group_of(app, pidx) == group_index, identifies as
        # the node's public address, never beacons (the parent aggregates),
        # and adopts handed-off client sockets over the control channel
        self.group_spec = group_spec or None
        self.block_service_provider = block_service_provider
        # [pegasus.clusters]: remote cluster name -> meta address list, the
        # duplication target directory (reference pegasus_const cluster
        # section; dup entries name clusters, this resolves them)
        self.remote_clusters = {k: (v if isinstance(v, list) else [v])
                                for k, v in (remote_clusters or {}).items()}
        self.cluster_id = cluster_id
        self.options_factory = options_factory or (lambda: EngineOptions(backend="cpu"))
        self.pool = ConnectionPool()
        self._lock = threading.RLock()
        self._replicas = {}      # (app_id, pidx) -> Replica
        # data-integrity plane (ISSUE 17): partitions pulled off the
        # serving path after a corruption hit; gpid "a.p" -> forensics
        # record. Reported in beacons (status QUARANTINED) so the meta
        # re-seeds and the doctor names them; cleared on re-open.
        self._quarantined = {}   #: guarded_by self._lock
        # gpids with an async read-path quarantine already in flight
        self._quarantining = set()  #: guarded_by self._lock
        # (app_id, pidx) -> monotonic ts of the last background scrub
        self._last_scrub = {}    #: guarded_by self._lock
        self._scrub_interval = float(
            os.environ.get("PEGASUS_SCRUB_INTERVAL_S", "300"))
        self._scrub_bps = float(os.environ.get("PEGASUS_SCRUB_BPS", "0"))
        self._quarantine_keep = int(
            os.environ.get("PEGASUS_QUARANTINE_KEEP", "4"))
        self._service = ReplicaService()
        self._service.set_write_router(self._route_write)
        self.rpc = RpcServer(host, port)
        self.rpc.register_serverlet(self._service)
        self.rpc.register(RPC_OPEN_REPLICA, self._on_open_replica)
        self.rpc.register(RPC_CLOSE_REPLICA, self._on_close_replica)
        self.rpc.register(RPC_REPLICA_STATE, self._on_replica_state)
        from ..meta.meta_server import RPC_QUERY_REPLICA_INFO

        self.rpc.register(RPC_QUERY_REPLICA_INFO, self._on_query_replica_info)
        from ..meta.meta_server import RPC_BULK_LOAD, RPC_COLD_BACKUP

        self.rpc.register(RPC_COLD_BACKUP, self._on_cold_backup)
        self.rpc.register(RPC_BULK_LOAD, self._on_bulk_load)
        self.rpc.register(RPC_PREPARE, self._on_prepare)
        self.rpc.register(RPC_LEARN, self._on_learn)
        self.rpc.register(RPC_LEARN_PREPARE, self._on_learn_prepare)
        self.rpc.register(RPC_LEARN_FETCH, self._on_learn_fetch)
        self.rpc.register(RPC_LEARN_TAIL, self._on_learn_tail)
        self.rpc.register(RPC_LEARN_FINISH, self._on_learn_finish)
        from ..runtime.remote_command import RemoteCommandService

        self.commands = RemoteCommandService()
        self.commands.register_defaults(node_kind="replica",
                                        describe=self._describe)
        self.commands.register("manual-compact", self._cmd_manual_compact)
        self.commands.register("batched-manual-compact",
                               self._cmd_batched_manual_compact)
        self.commands.register("replica-disk", self._cmd_replica_disk)
        self.commands.register("query-compact-state", self._cmd_compact_state)
        self.commands.register("detect_hotkey", self._cmd_detect_hotkey)
        self.commands.register("set-read-residency",
                               self._cmd_set_read_residency)
        self.commands.register("flush-log", self._cmd_flush_log)
        self.commands.register("trigger-audit", self._cmd_trigger_audit)
        self.commands.register("query-audit", self._cmd_query_audit)
        self.commands.register("compact-sched-policy",
                               self._cmd_compact_sched_policy)
        self.commands.register("compact-sched-status",
                               self._cmd_compact_sched_status)
        self.commands.register("learn-status", self._cmd_learn_status)
        self.commands.register("scrub-replica", self._cmd_scrub_replica)
        self.commands.register("quarantine-replica",
                               self._cmd_quarantine_replica)
        self.commands.register("quarantine-status",
                               self._cmd_quarantine_status)
        self.rpc.register(RPC_REMOTE_COMMAND, self.commands.rpc_handler)
        self.rpc.start()
        self.address = f"{self.rpc.address[0]}:{self.rpc.address[1]}"
        if self.group_spec:
            from .serve_groups import RPC_GROUP_STATE

            # replica naming / primary identity must be the PUBLIC address
            # the meta assigned to this node, not the worker's private port
            self.address = self.group_spec["public_address"]
            self.rpc.register(RPC_GROUP_STATE, self._on_group_state)
            # bind BEFORE the parent can read GROUP_READY; only accept()
            # runs on the thread
            srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            srv.bind(self.group_spec["control_path"])
            srv.listen(2)
            self._adoption_srv = srv
            spawn_thread(self._adoption_loop, daemon=True)
        self._stop = threading.Event()
        self._beacon_threads = {}  # meta addr -> in-flight ping thread
        self._beacon_thread = spawn_thread(self._beacon_loop, daemon=True,
                                           start=False)
        self._maint_thread = spawn_thread(self._maintenance_loop,
                                          daemon=True, start=False)

    def start(self, beacon_interval: float = 1.0,
              maintenance_interval: float = 60.0) -> "ReplicaStub":
        self._beacon_interval = beacon_interval
        self._maint_interval = maintenance_interval
        if not self.group_spec:   # a group worker's parent beacons for it
            self.send_beacon()
            self._beacon_thread.start()
        self._maint_thread.start()
        # flight recorder (ISSUE 12): every serving process samples its
        # counter registry into the history ring (refcounted process-wide
        # sampler — group workers are their own processes and get their
        # own, exactly like their own registry)
        from ..runtime.metric_history import HISTORY

        HISTORY.start()
        return self

    # --------------------------------------------- group-executor plumbing

    def _beacon_fragment_locked(self):  #: requires self._lock
        from ..runtime.perf_counters import counters

        alive = [f"{a}.{p}" for (a, p) in self._replicas]
        progress = []
        states = []
        for (a, p), rep in self._replicas.items():
            # dict() snapshot: _sync_duplications swaps the mapping
            # copy-on-write, so iteration here can never see a resize
            for dupid, d in dict(rep.duplicators).items():
                progress.append(f"{a}.{p}.{dupid}:{d.last_shipped_decree}")
                # duplicator ship-lag: decrees committed here but not yet
                # confirmed shipped (refreshed every beacon tick)
                counters.number(f"dup.lag.{a}.{p}.{dupid}").set(
                    max(0, rep.last_committed - d.last_shipped_decree))
            st = {"gpid": f"{a}.{p}", "status": rep.status,
                  "ballot": rep.ballot,
                  "committed": rep.last_committed,
                  "applied": rep.server.engine.last_committed_decree(),
                  "prepared": rep.last_prepared,
                  # compaction-debt plane (ISSUE 10): the scheduler folds
                  # this out of the meta's cluster-state snapshot; the
                  # call also refreshes the engine.compact.<a>.<p>.*
                  # gauges so every surface reads the same fold
                  "compact": rep.compact_debt()}
            la = rep.server.last_audit
            if la:
                st["audit"] = {"audit_id": la.get("audit_id", 0),
                               "decree": la.get("decree", 0),
                               "digest": la.get("digest", "")}
            states.append(json.dumps(st))
        # quarantined partitions ride the same state list as synthetic
        # entries: the meta's beacon fold sees status QUARANTINED and
        # treats the replica as lost (repair_quarantined), the doctor
        # names it — no wire-schema change needed
        for gpid, q in self._quarantined.items():
            states.append(json.dumps({"gpid": gpid, "status": "QUARANTINED",
                                      "quarantine": q}))
        # tenant ledger fragment (ISSUE 18): one synthetic entry carrying
        # this PROCESS's per-table totals, keyed by pid so group workers'
        # fragments survive the meta fold (which keys by gpid) next to
        # the parent's. Refresh the device-plane gauges first — per-table
        # HBM from the hosted engines, device seconds/offload bytes from
        # the causal-job window — so the shipped snapshot is current.
        frag = self._table_stats_fragment()
        if frag is not None:
            states.append(frag)
        return alive, progress, states

    def _table_stats_fragment(self):
        """json.dumps'd synthetic beacon entry with TABLE_STATS.snapshot(),
        or None when no table is wired in this process. The meta diverts
        status TABLE_STATS into its tables-only side map (_node_tables)
        at ingestion, so replica-state consumers (doctor lag fold,
        quarantine repair, scheduler debt) never iterate over it."""
        from ..runtime.job_trace import JOB_TRACER
        from ..runtime.table_stats import TABLE_STATS

        if not TABLE_STATS.tables():
            return None
        hbm = {}
        for (a, p), rep in self._replicas.items():
            name = TABLE_STATS.table_for_gpid(f"{a}.{p}")
            if name:
                hbm[name] = (hbm.get(name, 0)
                             + rep.server.engine.device_resident_bytes())
        for name, nbytes in hbm.items():
            TABLE_STATS.ledger(name).set_hbm_resident(nbytes)
        TABLE_STATS.attribute_jobs(JOB_TRACER.window(None))
        return json.dumps({"gpid": f"tables@pid:{os.getpid()}",
                           "status": "TABLE_STATS",
                           "tables": TABLE_STATS.snapshot()})

    def _on_group_state(self, header, body) -> bytes:
        """The parent's beacon-aggregation scrape: this worker's share of
        the node beacon (alive replicas + duplication progress + the
        per-replica lag/audit states the cluster doctor folds)."""
        with self._lock:
            alive, progress, states = self._beacon_fragment_locked()
        return json.dumps({"alive": alive, "dup_progress": progress,
                           "states": states}).encode("utf-8")

    def _owns(self, app_id: int, pidx: int) -> bool:
        if not self.group_spec:
            return True
        from .serve_groups import group_of

        return group_of(app_id, pidx, self.group_spec["group_count"]) \
            == self.group_spec["group_index"]

    def _adoption_loop(self):
        """Accept the parent's control connection and adopt handed-off
        client sockets (SCM_RIGHTS + length-prefixed already-read bytes).
        EOF on the control stream means the parent is gone: exit — an
        orphan worker must never outlive its node."""
        import struct as _struct

        srv = self._adoption_srv
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            try:
                while True:
                    msg, fds, _, _ = socket.recv_fds(conn, 1 << 16, 4)
                    if not msg and not fds:
                        raise ConnectionError("parent closed")
                    while len(msg) < 4:
                        chunk = conn.recv(4 - len(msg))
                        if not chunk:
                            raise ConnectionError("parent closed")
                        msg += chunk
                    (need,) = _struct.unpack("<I", msg[:4])
                    payload = bytearray(msg[4:])
                    while len(payload) < need:
                        chunk = conn.recv(min(1 << 16, need - len(payload)))
                        if not chunk:
                            raise ConnectionError("parent closed")
                        payload += chunk
                    if fds:
                        sock = socket.socket(fileno=fds[0])
                        for extra in fds[1:]:
                            os.close(extra)
                        self.rpc.serve_adopted(sock, bytes(payload))
                    conn.sendall(b"A")
            except (ConnectionError, OSError):
                pass
            # the parent never reconnects a control stream: it restarts
            # the whole worker instead — treat EOF as a death sentence
            os._exit(0)

    def _maintenance_loop(self):
        """Per-replica timers (the reference's replica-level checkpoint timer
        + manual-compact trigger checks, SURVEY §3.1/§3.5): periodic async
        checkpoint, plog GC behind the durable decree, and env-driven
        periodic manual compaction."""
        while not self._stop.wait(self._maint_interval):
            with self._lock:
                reps = list(self._replicas.values())
            for rep in reps:
                try:
                    rep.server.engine.async_checkpoint()
                    rep.gc_log()
                    rep.server.manual_compact_service \
                        .start_manual_compact_if_needed(rep.server.app_envs)
                except Exception as e:  # keep the timer alive
                    print(f"[maintenance] {rep.name}: {e!r}", flush=True)
            # idle retry of a scheduler-held L0 trigger: debt a lapsed
            # defer token or a freed device gate left above the trigger
            # must compact without waiting for the next flush. AFTER
            # the light per-replica work, and at most ONE synchronous
            # compaction per tick — a multi-second merge must not stall
            # every other replica's checkpoint/GC behind it
            for rep in reps:
                try:
                    if rep.server.engine.poke_compaction():
                        break
                except Exception as e:
                    print(f"[maintenance] {rep.name}: {e!r}", flush=True)
            # background scrub (ISSUE 17): re-verify on-disk checksums off
            # the serving path, one replica per tick past its cadence —
            # rate-limited inside engine.scrub so a cold multi-GB replica
            # can't starve the other timers for long
            try:
                self._scrub_tick(reps)
            except Exception as e:
                print(f"[maintenance] scrub: {e!r}", flush=True)

    # ------------------------------------------------------------- beacons

    def _beacon_loop(self):
        while not self._stop.wait(self._beacon_interval):
            try:
                self.send_beacon()
            except Exception as e:  # ANY error: a dead beacon thread gets
                # this healthy node declared dead after fd_grace
                print(f"[beacon] {self.address}: {e!r}", flush=True)

    def send_beacon(self):
        with self._lock:
            alive, progress, states = self._beacon_fragment_locked()
        req = mm.BeaconRequest(node=self.address, alive_replicas=alive,
                               dup_progress=progress, replica_states=states)
        body = codec.encode(req)
        # beacon EVERY configured meta, not just the first reachable one:
        # follower metas absorb beacons too (meta HA — a warm liveness map
        # makes leader takeover instant instead of re-declaring the world
        # dead), and a node partitioned from the leader still registers
        # with whoever can hear it. CONCURRENTLY: sequential 5s timeouts
        # with two black-holed metas ahead of the leader would eat ~10s of
        # the fd grace per round and get a healthy node declared dead.
        def ping(meta):
            host, _, port = meta.rpartition(":")
            try:
                conn = self.pool.get((host, int(port)))
                conn.call(RPC_FD_BEACON, body, timeout=2.0)
            except (RpcError, OSError):
                pass
        if len(self.meta_addrs) == 1:
            ping(self.meta_addrs[0])
            return
        # at most ONE in-flight ping per meta: a black-holed meta blocks
        # its thread ~connect-timeout seconds while beacons fire every
        # second — respawning per round would pile up threads without bound
        threads = []
        for m in self.meta_addrs:
            prev = self._beacon_threads.get(m)
            if prev is not None and prev.is_alive():
                continue
            t = spawn_thread(ping, m, daemon=True, start=False,
                             name=f"beacon:{self.address}->{m}")
            self._beacon_threads[m] = t
            threads.append(t)
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=2.5)

    # ------------------------------------------------- meta-driven lifecycle

    def _on_open_replica(self, header, body) -> bytes:
        req = codec.decode(mm.OpenReplicaRequest, body)
        if not self._owns(req.app_id, req.pidx):
            raise RpcError(ERR_INVALID_STATE,
                           f"partition {req.app_id}.{req.pidx} belongs to "
                           f"another group executor")
        key = (req.app_id, req.pidx)
        # a CROSS-partition learn is split child seeding (parent history
        # copied once); a same-pidx learn is a repair/failover re-seed
        # from the partition's own authoritative primary
        cross_learn = bool(req.learn_from) and 0 <= req.learn_pidx != req.pidx
        with self._lock:
            rep = self._replicas.get(key)
            if rep is None:
                path = os.path.join(self.root, f"{req.app_id}.{req.pidx}")
                if req.restore_dir and not os.path.exists(
                        os.path.join(path, "data", "MANIFEST")):
                    self._seed_from_restore(path, req.restore_dir)
                rep = Replica(f"{self.address}", path, req.app_id, req.pidx,
                              self.options_factory(),
                              peers=self._peer_factory(req.app_id, req.pidx),
                              cluster_id=self.cluster_id)
                # read-path corruption -> async quarantine; the Replica
                # re-installs the hook on every engine swap (learn re-seed)
                rep.set_corruption_hook(
                    self._corruption_hook(req.app_id, req.pidx))
                self._replicas[key] = rep
                # a re-open after quarantine is the heal: the meta seeded a
                # fresh learner dir — the partition is serving again
                self._quarantined.pop(f"{req.app_id}.{req.pidx}", None)
            # Split seeding must be ONCE-ONLY and seed-before-serve:
            #  * once-only — when the meta retries a split whose seeding
            #    RPC failed (timeout/partial), a child that DID seed and
            #    then accepted writes must not re-learn from the parent:
            #    the parent has rejected child-half writes since split
            #    phase 1, so its copy lacks them, and learn_from replaces
            #    the engine wholesale — the re-learn would silently wipe
            #    acked writes (the cross-cluster digest compare caught
            #    exactly this: the duplication target kept rows the
            #    re-learned source child had lost);
            #  * seed-before-serve — registering the child before its
            #    seed learn makes a child whose learn then fails servable
            #    EMPTY (clients would write into a hollow partition whose
            #    pre-split half later reads as lost), so a child pending
            #    its seed is registered only after the learn succeeds.
            seeded = getattr(rep, "split_seeded", False) \
                or rep.last_committed > 0
            need_seed = cross_learn and not seeded
            if not need_seed:
                # (re-)register: partition splits change the count for
                # existing replicas, which drives the misroute rejection
                self._service.add_replica(rep.server, req.partition_count)
        learn_self = (req.learn_from == self.address
                      and (req.learn_pidx < 0 or req.learn_pidx == req.pidx))
        if req.learn_from and not learn_self and (need_seed
                                                  or not cross_learn):
            learn_pidx = req.learn_pidx if req.learn_pidx >= 0 else req.pidx
            if req.learn_from == self.address:
                with self._lock:
                    src = self._replicas.get((req.app_id, learn_pidx))
                peer = src  # in-process parent (split on the same node)
                if peer is None and self.group_spec \
                        and not self._owns(req.app_id, learn_pidx):
                    # split across group executors: the parent partition
                    # lives in a SIBLING group's process — learn over RPC
                    # through the node's public router, which hands the
                    # LEARN to the owning group
                    peer = _RemotePeer(self, req.learn_from, req.app_id,
                                       learn_pidx)
            else:
                peer = _RemotePeer(self, req.learn_from, req.app_id, learn_pidx)
            if peer is not None:
                if need_seed:
                    from ..runtime import events

                    events.emit("split.seed_start",
                                gpid=f"{req.app_id}.{req.pidx}",
                                parent=f"{req.app_id}.{learn_pidx}",
                                source=req.learn_from)
                rep.learn_from(peer)
                with self._lock:
                    if cross_learn:
                        # seed complete: a split retry must never learn
                        # this child from its parent again
                        rep.split_seeded = True
                    self._service.remove_replica(req.app_id, req.pidx)
                    self._service.add_replica(rep.server, req.partition_count)
                if need_seed:
                    from ..runtime import events

                    events.emit("split.seeded",
                                gpid=f"{req.app_id}.{req.pidx}",
                                committed=rep.last_committed)
            elif need_seed:
                # no resolvable seed source (the in-process parent is gone,
                # e.g. mid-restart): replying success here would let the
                # meta count this child as seeded and spread the GC mask
                # over a hollow, unregistered partition — fail the open so
                # the split marks seeding incomplete and retries
                raise RpcError(ERR_INVALID_STATE,
                               f"split child {req.app_id}.{req.pidx} cannot "
                               f"seed: parent {req.app_id}.{learn_pidx} not "
                               f"found at {req.learn_from}")
        rep.app_name = req.app_name or rep.app_name
        if rep.app_name:
            # tenant accounting (ISSUE 18): the open request is where a
            # replica host learns which TABLE a partition serves
            rep.server.set_table_name(rep.app_name)
        rep.partition_count = req.partition_count or rep.partition_count
        rep.assume_view(GroupView(req.ballot, req.primary, req.secondaries))
        envs = json.loads(req.envs_json or "{}")
        if envs:
            rep.server.update_app_envs(envs)
        self._sync_duplications(rep)
        return codec.encode(mm.OpenReplicaResponse(
            last_committed=rep.last_committed, last_prepared=rep.last_prepared))

    def _sync_duplications(self, rep) -> None:
        """Reconcile the replica's mutation shippers against the dup entries
        the meta mirrors into the reserved app-env. Only the PRIMARY ships
        (the reference's duplication also runs on primaries); a demoted or
        removed primary tears its shippers down, a promoted one builds them
        and catches up from its plog + persisted confirmed decree."""
        from ..base import consts
        from ..client import MetaResolver
        from .duplicator import MutationDuplicator

        try:
            entries = json.loads(
                rep.server.app_envs.get(consts.ENV_DUPLICATION_KEY, "[]"))
        except ValueError:
            entries = []
        is_primary = rep.view is not None and rep.view.primary == rep.name
        want = {}
        if is_primary:
            for e in entries:
                if e.get("status") in ("start", "pause"):
                    want[int(e["dupid"])] = e
        # copy-on-write: concurrent readers (beacon thread, gc_log) snapshot
        # the mapping, so reconcile into a copy and swap it in at the end
        dups = dict(rep.duplicators)
        for dupid in list(dups):
            if dupid not in want:
                d = dups.pop(dupid)
                try:
                    rep.commit_hooks.remove(d.on_commit)
                except ValueError:
                    pass
                d.stop()
                from ..runtime.perf_counters import counters

                counters.remove(f"dup.lag.{rep.app_id}.{rep.pidx}.{dupid}")
        for dupid, e in want.items():
            d = dups.get(dupid)
            if d is None:
                metas = self.remote_clusters.get(e["remote"])
                if not metas:
                    print(f"[dup {dupid}] unknown remote cluster "
                          f"{e['remote']!r} (configure [pegasus.clusters])",
                          flush=True)
                    continue
                try:
                    resolver = MetaResolver(list(metas), rep.app_name)
                except Exception as ex:  # remote may be down; retry on next
                    print(f"[dup {dupid}] remote resolve failed: {ex!r}",
                          flush=True)                     # view/env install
                    continue
                floor = int(e.get("confirmed", {}).get(str(rep.pidx), 0))
                # born paused: catch_up must order the plog backlog ahead of
                # live hook traffic before anything ships, or a live decree
                # would advance the confirmed point past the backlog
                d = MutationDuplicator(
                    resolver, cluster_id=self.cluster_id,
                    fail_mode=e.get("fail_mode", "slow"), dupid=dupid,
                    progress_dir=os.path.join(rep.path, "dup"),
                    confirmed_floor=floor, paused=True)
                dups[dupid] = d
                rep.commit_hooks.append(d.on_commit)
                d.catch_up(rep.plog)
            d.fail_mode = e.get("fail_mode", "slow")
            d.set_paused(e.get("status") == "pause")
        rep.duplicators = dups

    def batched_manual_compact(self, app_id: int = None, now: int = None,
                               mesh=None) -> dict:
        """Node-level manual compaction: ALL this node's (optionally one
        app's) tpu-backend replicas compact in batched device dispatches —
        ops.batched_compact's dp-over-partitions as a SYSTEM operation,
        replacing N sequential per-replica CompactRange jobs with
        ceil(N/chunk) vmapped kernel launches. Replicas whose runs cannot
        be device-cached fall back to their own manual_compact.

        Every participating engine's compaction lock is held from file-set
        snapshot through output install (acquired in stable key order), so
        concurrent flush-triggered compactions cannot double-merge."""
        from ..ops.batched_compact import compact_partition_batch
        from ..ops.compact import CompactOptions

        from ..engine.db import META_LAST_MANUAL_COMPACT_FINISH_TIME

        def mark_done(eng):
            with eng._lock:
                eng._meta[META_LAST_MANUAL_COMPACT_FINISH_TIME] = \
                    int(time.time())
                eng._write_manifest_locked()  # finish time must persist

        with self._lock:
            reps = [(aid, rep)
                    for (aid, p), rep in sorted(self._replicas.items())
                    if app_id is None or aid == app_id]
        groups, fallback = {}, []
        held = set()  # engines whose compaction lock we currently hold

        def release(eng):
            if eng in held:
                held.discard(eng)
                eng._compaction_lock.release()

        stats = {"input_records": 0, "output_records": 0,
                 "partitions": 0, "batched": 0, "fallback": 0}
        try:
            for aid, rep in reps:
                eng = rep.server.engine
                if eng.opts.backend != "tpu":
                    fallback.append(rep)
                    continue
                eng.flush()
                eng._compaction_lock.acquire()
                held.add(eng)
                with eng._lock:
                    all_inputs = list(eng._l0)
                    for lv in sorted(eng._levels):
                        all_inputs.extend(eng._levels[lv])
                inputs = [s for s in all_inputs if s.n]
                if not inputs:
                    # nothing to merge — but zero-record SSTs (possible
                    # when a merge drops everything) must still be swept,
                    # as manual_compact's full-input merge would do
                    if all_inputs:
                        from ..engine.block import KVBlock

                        eng._install_merge_output(all_inputs, [],
                                                  KVBlock.empty(),
                                                  eng.opts.max_levels)
                    mark_done(eng)
                    release(eng)
                    stats["partitions"] += 1
                    stats["batched"] += 1
                    continue
                device_runs = [eng._device_run_budgeted(s) for s in inputs]
                if any(d is None for d in device_runs):
                    release(eng)  # its own manual_compact re-locks later
                    fallback.append(rep)
                    continue
                # dispatches group by (app, partition_mask): the mask
                # broadcasts in-kernel, and a mask change mid-env-spread
                # must not leak one replica's mask onto another. The HOST
                # post passes (user rules, default_ttl) use each engine's
                # OWN options via post_opts.
                groups.setdefault((aid, eng.opts.partition_mask),
                                  []).append((eng, all_inputs, inputs,
                                              device_runs))
            for (aid, pmask), group in groups.items():
                opts = CompactOptions(
                    now=now, bottommost=True, runs_sorted=True,
                    backend="tpu", partition_mask=pmask,
                    prefix_u32=group[0][0].opts.prefix_u32)
                jobs, post_opts = [], []
                for eng, all_inputs, inputs, drs in group:
                    jobs.append(([s.block() for s in inputs], drs,
                                 eng.opts.pidx))
                    post_opts.append(CompactOptions(
                        now=now, bottommost=True, runs_sorted=True,
                        backend="tpu", pidx=eng.opts.pidx,
                        partition_mask=pmask,
                        prefix_u32=eng.opts.prefix_u32,
                        default_ttl=eng.opts.default_ttl,
                        user_ops=tuple(eng.opts.user_ops)))
                outs = compact_partition_batch(jobs, opts, mesh=mesh,
                                               post_opts=post_opts)
                for (eng, all_inputs, inputs, _), out in zip(group, outs):
                    n_in = sum(s.n for s in inputs)
                    # remove EVERY input file incl. zero-record ones
                    eng._install_merge_output(all_inputs, [], out,
                                              eng.opts.max_levels)
                    mark_done(eng)
                    # this engine is done: let flush-triggered compactions
                    # proceed instead of stalling on other groups' work
                    release(eng)
                    stats["input_records"] += n_in
                    stats["output_records"] += out.n
                    stats["partitions"] += 1
                    stats["batched"] += 1
        finally:
            for eng in list(held):
                release(eng)
        for rep in fallback:
            fs = rep.server.engine.manual_compact(now=now)
            stats["input_records"] += fs.get("input_records", 0)
            stats["output_records"] += fs.get("output_records", 0)
            stats["partitions"] += 1
            stats["fallback"] += 1
        return stats

    def _cmd_replica_disk(self, args) -> str:
        """Per-replica on-disk footprint (the shell app_disk scrape)."""
        with self._lock:
            reps = list(self._replicas.items())
        out = {}
        for (aid, pidx), rep in reps:
            eng = rep.server.engine
            with eng._lock:
                files = list(eng._l0) + [f for fs in eng._levels.values()
                                         for f in fs]
            out[f"{aid}.{pidx}"] = {
                "sst_bytes": sum(f.data_bytes for f in files),
                "sst_files": len(files),
                "records": sum(f.n for f in files),
                "primary": rep.status == "PRIMARY",
            }
        return json.dumps(out)

    def _cmd_batched_manual_compact(self, args) -> str:
        app_id = int(args[0]) if args else None
        stats = self.batched_manual_compact(app_id=app_id)
        return json.dumps(stats)

    def _on_query_replica_info(self, header, body) -> bytes:
        """Everything this node holds — the disaster-recovery scan the meta
        `recover` command aggregates (reference query_replica_info)."""
        with self._lock:
            reps = list(self._replicas.values())
        out = []
        for rep in reps:
            out.append(mm.ReplicaInfo(
                app_name=rep.app_name, app_id=rep.app_id, pidx=rep.pidx,
                partition_count=rep.partition_count, ballot=rep.ballot,
                last_committed=rep.last_committed,
                last_prepared=rep.last_prepared,
                last_durable=rep.server.engine.last_durable_decree(),
                envs_json=json.dumps(rep.server.app_envs),
                last_applied=rep.server.engine.last_committed_decree()))
        return codec.encode(mm.QueryReplicaInfoResponse(replicas=out))

    def _seed_from_restore(self, replica_path: str, restore_dir: str) -> None:
        """Pre-open restore: download backup checkpoint files into the data
        dir through the block service (reference restore at open,
        pegasus_server_impl.cpp:1339)."""
        from ..runtime.block_service import create_block_service

        data = os.path.join(replica_path, "data")
        bs = create_block_service(self.block_service_provider, "/")
        bs.download_dir(restore_dir, data)

    def _on_close_replica(self, header, body) -> bytes:
        req = codec.decode(mm.CloseReplicaRequest, body)
        with self._lock:
            rep = self._replicas.pop((req.app_id, req.pidx), None)
            self._service.remove_replica(req.app_id, req.pidx)
            # a close is also the meta's quarantine ack (the re-seed may
            # have landed on another node): stop beaconing the lost copy
            self._quarantined.pop(f"{req.app_id}.{req.pidx}", None)
            self._quarantining.discard(f"{req.app_id}.{req.pidx}")
        if rep:
            rep.close()
        return b""

    # ------------------------------------- data integrity plane (ISSUE 17)

    def _corruption_hook(self, app_id: int, pidx: int):
        """Build the engine's read-path corruption callout for one
        partition: hand off to an async quarantine thread (the engine
        cannot close itself from inside a failing read) with in-flight
        dedup so a burst of reads against the same rotten SST spawns
        exactly one quarantine."""
        gpid = f"{app_id}.{pidx}"

        def on_corruption(exc):
            with self._lock:
                if gpid in self._quarantining or gpid in self._quarantined \
                        or (app_id, pidx) not in self._replicas:
                    return
                self._quarantining.add(gpid)
            spawn_thread(self.quarantine_replica, app_id, pidx,
                         str(getattr(exc, "detail", None) or exc), "read",
                         daemon=True, name=f"quarantine.{gpid}")

        return on_corruption

    def quarantine_replica(self, app_id: int, pidx: int, reason: str,
                           source: str = "command") -> dict:
        """Pull one partition off the serving path after a corruption hit
        (read path, scrub finding, or an audit-named mismatch): unregister
        it so clients get typed errors instead of garbage, close it, move
        its data dir into a bounded-retention `quarantine/` forensics dir,
        and record the state so beacons report QUARANTINED — the meta then
        re-seeds the partition elsewhere/afresh like any lost replica."""
        from ..runtime import events
        from ..runtime.perf_counters import counters

        gpid = f"{app_id}.{pidx}"
        key = (app_id, pidx)
        with self._lock:
            rep = self._replicas.pop(key, None)
            if rep is None:
                self._quarantining.discard(gpid)
                prior = self._quarantined.get(gpid)
                return dict(prior) if prior else {"error": f"no replica {gpid}"}
            self._service.remove_replica(app_id, pidx)
            self._last_scrub.pop(key, None)
        try:
            rep.close()
        except Exception as e:  # noqa: BLE001 - forensics move still runs
            print(f"[quarantine] {gpid}: close failed: {e!r}", flush=True)
        qroot = os.path.join(self.root, "quarantine")
        dest = os.path.join(qroot, f"{gpid}.{int(time.time() * 1000)}")
        try:
            os.makedirs(qroot, exist_ok=True)
            os.rename(rep.path, dest)
        except OSError as e:
            print(f"[quarantine] {gpid}: move failed: {e!r}", flush=True)
            dest = ""
        self._prune_quarantine(qroot)
        record = {"reason": reason, "source": source, "dir": dest,
                  "ts": time.time()}
        with self._lock:
            self._quarantined[gpid] = record
            self._quarantining.discard(gpid)
        counters.rate("replica.quarantine_count").increment()
        events.emit("replica.quarantine", "error", gpid=gpid,
                    node=self.address, reason=reason, source=source)
        return dict(record)

    def _prune_quarantine(self, qroot: str) -> None:
        """Bound the forensics dir: keep the newest PEGASUS_QUARANTINE_KEEP
        quarantined trees, delete the rest oldest-first."""
        import shutil

        try:
            entries = [os.path.join(qroot, n) for n in os.listdir(qroot)]
        except OSError:
            return
        entries.sort(key=lambda p: os.path.getmtime(p)
                     if os.path.exists(p) else 0.0)
        for victim in entries[:max(0, len(entries) - self._quarantine_keep)]:
            shutil.rmtree(victim, ignore_errors=True)

    def _scrub_tick(self, reps) -> None:
        """Maintenance-timer scrub cadence: pick at most ONE replica past
        its PEGASUS_SCRUB_INTERVAL_S and re-verify its on-disk checksums."""
        if self._scrub_interval <= 0:
            return
        now = time.monotonic()
        victim = None
        with self._lock:
            oldest = None
            for rep in reps:
                k = (rep.app_id, rep.pidx)
                if k not in self._replicas:
                    continue  # closed/quarantined since the snapshot
                last = self._last_scrub.get(k, 0.0)
                # OLDEST past-due replica, not the first in dict order: a
                # cadence shorter than the maintenance interval leaves every
                # replica past due at every tick, and first-match would
                # re-scrub one replica forever while the rest starve
                if (now - last >= self._scrub_interval
                        and (oldest is None or last < oldest)):
                    oldest = last
                    victim = rep
            if victim is not None:
                self._last_scrub[(victim.app_id, victim.pidx)] = now
        if victim is not None:
            self._scrub_replica(victim)

    def _scrub_replica(self, rep) -> dict:
        """Scrub one replica (engine-side checksum + manifest re-verify)
        and quarantine it on any finding. Never touches lane guards: the
        scrub is pure host-side file I/O under the engine's job tracer."""
        res = rep.server.engine.scrub(
            rate_bytes_per_s=self._scrub_bps or None)
        if res["findings"]:
            f0 = res["findings"][0]
            self.quarantine_replica(
                rep.app_id, rep.pidx,
                f"scrub: {f0.get('detail', '?')} ({f0.get('path', '?')})",
                "scrub")
            res["quarantined"] = True
        return res

    def _cmd_scrub_replica(self, args: list) -> str:
        """`scrub-replica [app_id.pidx]`: synchronously re-verify hosted
        replicas' on-disk checksums now (all hosted replicas, or just the
        named gpid). JSON keyed by gpid so the group router merges worker
        shards structurally."""
        with self._lock:
            targets = [(k, r) for k, r in self._replicas.items()]
        out = {}
        for (a, p), rep in targets:
            gpid = f"{a}.{p}"
            if args and args[0] != gpid:
                continue
            try:
                res = self._scrub_replica(rep)
            except Exception as e:  # noqa: BLE001 - report, don't drop shard
                out[gpid] = {"error": repr(e)}
                continue
            out[gpid] = {"files": res["files"], "bytes": res["bytes"],
                         "findings": res["findings"],
                         "errors": res.get("errors", []),
                         "quarantined": bool(res.get("quarantined"))}
        return json.dumps(out)

    def _cmd_quarantine_replica(self, args: list) -> str:
        """`quarantine-replica <app_id.pidx> [reason...]`: force one
        partition into quarantine (the collector's auto-heal driver uses
        this to convert an audit-named mismatch into a re-seed)."""
        if not args:
            return "usage: quarantine-replica <app_id.pidx> [reason]"
        a, _, p = args[0].partition(".")
        try:
            app_id, pidx = int(a), int(p)
        except ValueError:
            return f"bad gpid {args[0]!r}"
        reason = " ".join(args[1:]) or "remote-command"
        rec = self.quarantine_replica(app_id, pidx, reason, "command")
        if "error" in rec:
            return ""  # unhosted here: let the owning group's shard win
        return json.dumps({args[0]: rec})

    def _cmd_quarantine_status(self, args: list) -> str:
        """`quarantine-status`: this process's quarantined partitions
        (gpid-keyed JSON, group-router merge friendly)."""
        with self._lock:
            return json.dumps({g: dict(q)
                               for g, q in self._quarantined.items()})

    def _on_replica_state(self, header, body) -> bytes:
        req = codec.decode(mm.ReplicaStateRequest, body)
        with self._lock:
            rep = self._replicas.get((req.app_id, req.pidx))
        if rep is None:
            return codec.encode(mm.ReplicaStateResponse(error=1))
        return codec.encode(mm.ReplicaStateResponse(
            status=rep.status, ballot=rep.ballot,
            last_committed=rep.last_committed, last_prepared=rep.last_prepared,
            last_durable=rep.server.engine.last_durable_decree(),
            last_applied=rep.server.engine.last_committed_decree()))

    # ------------------------------------------------------- replication RPC

    def _peer_factory(self, app_id, pidx):
        def peers(addr: str):
            if addr == self.address:
                raise ConnectionError("self")
            return _RemotePeer(self, addr, app_id, pidx)

        return peers

    def _on_prepare(self, header, body) -> bytes:
        req = codec.decode(mm.PrepareRequest, body)
        with self._lock:
            rep = self._replicas.get((req.app_id, req.pidx))
        if rep is None:
            return codec.encode(mm.PrepareResponse(error=1, reason="no_replica"))
        if req.mutations:  # decree-pipelined window
            ms = [codec.decode(LogMutation, b) for b in req.mutations]
        elif req.mutation:  # single-mutation frame from an older sender
            ms = [codec.decode(LogMutation, req.mutation)]
        else:              # empty window: pure commit-point broadcast
            ms = []
        try:
            lp = rep.on_prepare_batch(req.ballot, ms, req.committed_decree)
            return codec.encode(mm.PrepareResponse(last_prepared=lp))
        except PrepareRejected as rej:
            return codec.encode(mm.PrepareResponse(
                error=1, reason=rej.reason, last_prepared=rej.last_prepared))

    def _on_learn(self, header, body) -> bytes:
        req = codec.decode(mm.LearnRequest, body)
        with self._lock:
            rep = self._replicas.get((req.app_id, req.pidx))
        if rep is None:
            return codec.encode(mm.LearnResponse(error=1))
        state = rep.fetch_learn_state()
        return codec.encode(mm.LearnResponse(
            files=[mm.FileBlob(n, d) for n, d in state["files"]],
            tail=[codec.encode(m) for m in state["tail"]],
            last_committed=state["last_committed"], ballot=state["ballot"]))

    # -------------------------------------------- block-shipped learn RPCs

    def _learn_replica(self, req):
        with self._lock:
            return self._replicas.get((req.app_id, req.pidx))

    def _on_learn_prepare(self, header, body) -> bytes:
        from ..rpc import messages as rpc_msg

        req = codec.decode(rpc_msg.LearnPrepareRequest, body)
        rep = self._learn_replica(req)
        if rep is None:
            return codec.encode(rpc_msg.LearnPrepareResponse(
                error=1, error_text="no_replica"))
        try:
            st = rep.prepare_learn_state(
                have=[{"name": e.name, "size": e.size, "digest": e.digest}
                      for e in req.have],
                delta=req.delta)
        except Exception as e:  # noqa: BLE001 - the learner retries
            return codec.encode(rpc_msg.LearnPrepareResponse(
                error=1, error_text=repr(e)))
        if req.job:
            # attribute this primary's checkpoint pin to the learner's
            # traced job (ISSUE 16) — opens a remote-view record here;
            # in a onebox the note lands straight in the learn timeline
            from ..runtime.job_trace import JOB_TRACER

            JOB_TRACER.note("learn.serve_prepare", job_id=req.job,
                            gpid=f"{req.app_id}.{req.pidx}",
                            blocks=len(st["blocks"]),
                            missing=len(st["missing"]))
        return codec.encode(rpc_msg.LearnPrepareResponse(
            learn_id=st["learn_id"], ckpt_decree=st["ckpt_decree"],
            ballot=st["ballot"], last_committed=st["last_committed"],
            blocks=[rpc_msg.LearnBlockEntry(e["name"], e["size"],
                                            e["digest"])
                    for e in st["blocks"]],
            missing=st["missing"], digest=st["digest"],
            digest_now=st["digest_now"], digest_pmask=st["digest_pmask"]))

    def _on_learn_fetch(self, header, body) -> bytes:
        from ..rpc import messages as rpc_msg

        req = codec.decode(rpc_msg.LearnFetchRequest, body)
        rep = self._learn_replica(req)
        if rep is None:
            return codec.encode(rpc_msg.LearnFetchResponse(
                error=1, error_text="no_replica"))
        try:
            ch = rep.fetch_learn_block(req.learn_id, req.name, req.offset,
                                       req.length)
        except Exception as e:  # noqa: BLE001 - incl. expired pins
            return codec.encode(rpc_msg.LearnFetchResponse(
                error=1, error_text=repr(e)))
        return codec.encode(rpc_msg.LearnFetchResponse(
            data=ch["data"], crc=ch["crc"], total=ch["total"]))

    def _on_learn_tail(self, header, body) -> bytes:
        from ..rpc import messages as rpc_msg

        req = codec.decode(rpc_msg.LearnTailRequest, body)
        rep = self._learn_replica(req)
        if rep is None:
            return codec.encode(rpc_msg.LearnTailResponse(
                error=1, error_text="no_replica"))
        try:
            st = rep.fetch_learn_tail(req.learn_id)
        except Exception as e:  # noqa: BLE001
            return codec.encode(rpc_msg.LearnTailResponse(
                error=1, error_text=repr(e)))
        return codec.encode(rpc_msg.LearnTailResponse(
            tail=[codec.encode(m) for m in st["tail"]],
            last_committed=st["last_committed"], ballot=st["ballot"]))

    def _on_learn_finish(self, header, body) -> bytes:
        from ..rpc import messages as rpc_msg

        req = codec.decode(rpc_msg.LearnFinishRequest, body)
        rep = self._learn_replica(req)
        if rep is not None:
            rep.finish_learn(req.learn_id)
        return codec.encode(rpc_msg.LearnFetchResponse())

    def _on_cold_backup(self, header, body) -> bytes:
        """Checkpoint this partition, then upload through the block service
        (reference: copy_checkpoint_to_dir -> block service upload)."""
        from ..runtime.block_service import create_block_service

        req = codec.decode(mm.OpenReplicaRequest, body)
        with self._lock:
            rep = self._replicas.get((req.app_id, req.pidx))
        if rep is None:
            raise RpcError(ERR_OBJECT_NOT_FOUND, "replica not served here")
        engine = rep.server.engine
        # hold the checkpoint lock across create+upload so a concurrent
        # maintenance checkpoint can neither GC this decree nor swap the
        # directory under the upload
        with engine.checkpoint_lock:
            decree = engine.sync_checkpoint()
            src = engine.get_checkpoint_dir(decree)
            bs = create_block_service(self.block_service_provider, "/")
            bs.upload_dir(src, req.restore_dir)
        return codec.encode(mm.OpenReplicaResponse(last_committed=decree))

    def _on_bulk_load(self, header, body) -> bytes:
        """Ingest this partition's bulk-load set from the provider root."""
        from ..engine import bulk_load as bl

        req = codec.decode(mm.OpenReplicaRequest, body)
        with self._lock:
            rep = self._replicas.get((req.app_id, req.pidx))
        if rep is None:
            raise RpcError(ERR_OBJECT_NOT_FOUND, "replica not served here")
        stats = bl.ingest_partition(
            rep.server.engine, req.restore_dir, req.app_name,
            req.partition_count, req.pidx, rep.server._schema)
        return int(stats["records"]).to_bytes(8, "little")

    # ------------------------------------------------------ remote commands

    def _describe(self) -> dict:
        with self._lock:
            return {
                "address": self.address,
                "replicas": {
                    f"{a}.{p}": {
                        "status": r.status, "ballot": r.ballot,
                        "last_committed": r.last_committed,
                        "last_prepared": r.last_prepared,
                        "last_durable": r.server.engine.last_durable_decree(),
                        "last_applied": r.server.engine.last_committed_decree(),
                    }
                    for (a, p), r in self._replicas.items()
                },
            }

    def _cmd_manual_compact(self, args: list) -> str:
        """manual-compact [app_id.pidx] — run a full compaction now."""
        done = []
        with self._lock:
            targets = list(self._replicas.items())
        for (a, p), rep in targets:
            if args and f"{a}.{p}" not in args:
                continue
            rep.server.manual_compact()
            done.append(f"{a}.{p}")
        return "compacted: " + ", ".join(done) if done else "no matching replica"

    def _cmd_compact_state(self, args: list) -> str:
        with self._lock:
            targets = list(self._replicas.items())
        return "\n".join(
            f"{a}.{p}: {rep.server.manual_compact_service.query_compact_state()}"
            for (a, p), rep in targets)

    def _cmd_detect_hotkey(self, args: list) -> str:
        """detect_hotkey <app_id.pidx> <read|write> <start|stop|query>."""
        if len(args) < 3:
            return "usage: detect_hotkey <app_id.pidx> <read|write> <start|stop|query>"
        gpid, kind, action = args[0], args[1], args[2]
        a, _, p = gpid.partition(".")
        with self._lock:
            rep = self._replicas.get((int(a), int(p)))
        if rep is None:
            return f"no replica {gpid}"
        return rep.server.on_detect_hotkey(kind, action)

    def _cmd_set_read_residency(self, args: list) -> str:
        """set-read-residency <app_id.pidx> <on|off> — pin/unpin one
        partition's SSTs HBM-resident for the device read path (the
        collector's hotkey loop drives this from read-hot verdicts)."""
        if len(args) < 2 or args[1] not in ("on", "off"):
            return "usage: set-read-residency <app_id.pidx> <on|off>"
        gpid = args[0]
        a, _, p = gpid.partition(".")
        with self._lock:
            rep = self._replicas.get((int(a), int(p)))
        if rep is None:
            return f"no replica {gpid}"
        on = args[1] == "on"
        rep.server.engine.set_read_residency(on)
        return f"read residency {'on' if on else 'off'} for {gpid}"

    def _cmd_trigger_audit(self, args: list) -> str:
        """trigger-audit <app_id.pidx> [audit_id] — ride a no-op mutation
        through the partition's PacificA prepare path so EVERY replica
        computes a consistency digest anchored at the same applied decree;
        then broadcast the commit point so idle secondaries apply it now.
        Must run on the primary. Returns the primary's digest as JSON; an
        empty reply means the partition is not served here (so a
        partition-group router's fan-out merge keeps the owner's reply)."""
        from ..base.utils import epoch_now
        from ..engine.server_impl import RPC_TRIGGER_AUDIT
        from ..rpc import messages as rpc_msg

        # now=<epoch>: auditor-supplied expiry anchor — the cross-cluster
        # compare digests BOTH clusters against one instant so a TTL
        # record expiring between the two audits cannot fake a mismatch
        now_arg = next((int(x[4:]) for x in args if x.startswith("now=")),
                       None)
        pos = [x for x in args if not x.startswith("now=")]
        if not pos:
            return ("usage: trigger-audit <app_id.pidx> [audit_id] "
                    "[now=<epoch>]")
        a, _, p = pos[0].partition(".")
        with self._lock:
            rep = self._replicas.get((int(a), int(p)))
        if rep is None:
            return ""
        if rep.status != PRIMARY:
            return json.dumps({"error": f"not primary ({rep.status})",
                               "gpid": pos[0], "node": self.address})
        audit_id = int(pos[1]) if len(pos) > 1 else int(time.time() * 1000)
        # partition_count - 1 = the ownership mask (hash % count == pidx);
        # carried IN the mutation so every replica digests against the
        # same mask at the same decree, mid-split or not
        pmask = max(0, (rep.partition_count or 0) - 1)
        req = rpc_msg.TriggerAuditRequest(
            audit_id=audit_id,
            now=epoch_now() if now_arg is None else now_arg, pmask=pmask)
        try:
            resp = rep.client_write(RPC_TRIGGER_AUDIT, req)
        except ReplicaError as e:
            return json.dumps({"error": str(e), "gpid": pos[0],
                               "node": self.address})
        if resp.error or not resp.digest:
            # a failed digest computation must surface as an ERROR the
            # audit driver turns into inconclusive — an empty digest
            # compared as real would fake a mismatch on every secondary
            return json.dumps({"error": f"digest failed ({resp.server})",
                               "gpid": pos[0], "node": self.address})
        rep.broadcast_commit_point()
        return json.dumps({"gpid": pos[0], "audit_id": audit_id,
                           "decree": resp.decree, "digest": resp.digest,
                           "records": resp.records, "node": self.address})

    def _cmd_query_audit(self, args: list) -> str:
        """query-audit [app_id.pidx] — each hosted (or the named) replica's
        latest decree-anchored digest plus its committed/applied decrees,
        keyed by gpid (JSON dict; disjoint keys merge cleanly through the
        partition-group router's structural fan-out merge)."""
        with self._lock:
            targets = list(self._replicas.items())
        out = {}
        for (a, p), rep in targets:
            gpid = f"{a}.{p}"
            if args and args[0] != gpid:
                continue
            ent = {"status": rep.status,
                   "committed": rep.last_committed,
                   "applied": rep.server.engine.last_committed_decree(),
                   "node": self.address}
            la = rep.server.last_audit
            if la:
                ent["audit"] = dict(la)
            out[gpid] = ent
        return json.dumps(out)

    def _cmd_compact_sched_policy(self, args: list) -> str:
        """compact-sched-policy <json> — the cluster compaction
        scheduler's delivery surface (ISSUE 10). The body is
        ``{"ttl_s": s, "decisions": {"<app>.<pidx>": {"policy":
        defer|normal|urgent, "reasons": [...]}}, "max_device": n?}``:
        each hosted partition named installs the policy token on its
        engine (expiring after ttl_s — a dead scheduler reverts to
        engine-local triggers), max_device caps this node's concurrent
        device compactions. Returns {gpid: policy} for what applied
        (disjoint keys merge cleanly through the group router)."""
        if not args:
            return "usage: compact-sched-policy <json>"
        try:
            req = json.loads(" ".join(args))
        except ValueError as e:
            return f"bad policy json: {e}"
        ttl = req.get("ttl_s")
        if "max_device" in req:
            from ..engine.db import SCHED_GATE

            # same lease as the tokens (set_max defaults the ttl): a
            # dead scheduler's cap expires back to the node's env
            # default instead of sticking forever. In partition-group
            # mode the command fans out to EVERY worker process and the
            # gate is per-process, so each worker takes its share of
            # the node cap (at least 1 — 0 would mean "no gate")
            cap = max(0, int(req["max_device"]))
            if cap > 0 and self.group_spec:
                cap = max(1, cap // self.group_spec["group_count"])
            SCHED_GATE.set_max(cap, ttl_s=ttl)
        with self._lock:
            reps = dict(self._replicas)
        applied = {}
        for gpid, dec in sorted((req.get("decisions") or {}).items()):
            a, _, p = gpid.partition(".")
            try:
                rep = reps.get((int(a), int(p)))
            except ValueError:
                continue
            if rep is None:
                continue
            policy = dec.get("policy", "normal")
            try:
                rep.server.engine.set_compact_policy(
                    policy, reasons=dec.get("reasons", ()), ttl_s=ttl,
                    job=dec.get("job", ""))
            except ValueError as e:
                applied[gpid] = f"error: {e}"
                continue
            if "where" in dec:
                # the placement half of the (when, where) pair (ISSUE
                # 14): same lease as the policy token — expiry reverts
                # this engine to local compaction
                rep.server.engine.set_offload_target(dec.get("where") or "",
                                                     ttl_s=ttl)
            applied[gpid] = policy
        return json.dumps(applied)

    def _cmd_compact_sched_status(self, args: list) -> str:
        """compact-sched-status [gpid] — each hosted (or the named)
        partition's live scheduler token (policy + the reasons that
        drove it + time to expiry) and its current compaction debt,
        keyed by gpid (JSON dict; disjoint keys merge cleanly through
        the group router's structural fan-out merge)."""
        with self._lock:
            targets = list(self._replicas.items())
        out = {}
        for (a, p), rep in targets:
            gpid = f"{a}.{p}"
            if args and args[0] != gpid:
                continue
            policy, reasons, expires_in = rep.server.engine.compact_policy()
            debt = rep.server.engine.compaction_debt()
            out[gpid] = {"policy": policy, "reasons": reasons,
                         "expires_in_s": round(expires_in, 3),
                         # the WHERE half (ISSUE 14): which compaction
                         # service this engine's merges ship to ("" =
                         # local), with the live-lease check applied
                         "offload": rep.server.engine.offload_target() or "",
                         "l0_files": debt["l0_files"],
                         "debt_bytes": debt["debt_bytes"],
                         "pending_installs": debt["pending_installs"],
                         "ceiling_files": debt["ceiling_files"],
                         "node": self.address}
        return json.dumps(out)

    def _cmd_learn_status(self, args: list) -> str:
        """learn-status — this process's block-ship totals (monotone, so
        the chaos harness can counter-assert the ship path was used)
        plus each hosted replica's learning flag and active primary-side
        learn pins. Shape is group-router-merge-friendly: the flat
        numeric `ship.*` totals SUM across worker processes and the
        per-gpid `replica.*` dicts are disjoint."""
        from ..runtime.perf_counters import counters

        with self._lock:
            targets = list(self._replicas.items())
        out = {
            "ship.blocks": counters.rate("learn.ship.blocks").total(),
            "ship.bytes": counters.rate("learn.ship.bytes").total(),
            "ship.delta_skipped_blocks": counters.rate(
                "learn.ship.delta_skipped_blocks").total(),
            "ship.replay_mutations": counters.rate(
                "learn.replay.mutations").total(),
        }
        for (a, p), rep in targets:
            ent = rep.learn_state()
            ent["pins"] = rep.learn_pins()
            ent["node"] = self.address
            out[f"replica.{a}.{p}"] = ent
        return json.dumps(out)

    def _cmd_flush_log(self, args: list) -> str:
        """flush-log: fsync every hosted replica's mutation log (reference
        flush_log remote command)."""
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            rep.plog.flush()
        return f"flushed {len(reps)} logs"

    # ------------------------------------------------------------ write path

    def _route_write(self, server, code, req):
        with self._lock:
            rep = self._replicas.get((server.app_id, server.pidx))
        if rep is None:
            raise RpcError(ERR_OBJECT_NOT_FOUND, "replica closed")
        if rep.status != PRIMARY:
            raise RpcError(ERR_INVALID_STATE, f"not primary ({rep.status})")
        try:
            return rep.client_write(code, req)
        except ReplicaError as e:
            raise RpcError(ERR_INVALID_STATE, str(e))

    # -------------------------------------------------------------- control

    def stop(self):
        if not self._stop.is_set():
            # drop the refcounted sampler ref ONCE: a chaos node-kill plus
            # the harness teardown both call stop(), and a double drop
            # would stop the sampler out from under the surviving stubs
            from ..runtime.metric_history import HISTORY

            HISTORY.stop()
        self._stop.set()
        if getattr(self, "_adoption_srv", None) is not None:
            try:
                self._adoption_srv.close()
            except OSError:
                pass
        self.rpc.stop()
        with self._lock:
            reps = list(self._replicas.values())
            self._replicas.clear()
        for r in reps:
            r.close()
        self.pool.close()
