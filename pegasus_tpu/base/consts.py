"""Table app-env key names and scan sentinels (src/base/pegasus_const.{h,cpp}).

App-envs are the per-table dynamic control surface: set through the meta
server, delivered to every replica, hot-applied by the engine
(reference: pegasus_server_impl::update_app_envs, src/server/pegasus_server_impl.cpp:2406).
"""

SCAN_CONTEXT_ID_VALID_MIN = 0
SCAN_CONTEXT_ID_COMPLETED = -1
SCAN_CONTEXT_ID_NOT_EXIST = -2

ENV_RESTORE_FORCE_RESTORE = "restore.force_restore"
ENV_RESTORE_POLICY_NAME = "restore.policy_name"
ENV_RESTORE_BACKUP_ID = "restore.backup_id"

ENV_USAGE_SCENARIO_KEY = "rocksdb.usage_scenario"
USAGE_SCENARIO_NORMAL = "normal"
USAGE_SCENARIO_PREFER_WRITE = "prefer_write"
USAGE_SCENARIO_BULK_LOAD = "bulk_load"

MANUAL_COMPACT_KEY_PREFIX = "manual_compact."
MANUAL_COMPACT_DISABLED_KEY = MANUAL_COMPACT_KEY_PREFIX + "disabled"
MANUAL_COMPACT_MAX_CONCURRENT_RUNNING_COUNT_KEY = (
    MANUAL_COMPACT_KEY_PREFIX + "max_concurrent_running_count"
)
MANUAL_COMPACT_PERIODIC_KEY_PREFIX = MANUAL_COMPACT_KEY_PREFIX + "periodic."
MANUAL_COMPACT_PERIODIC_TRIGGER_TIME_KEY = MANUAL_COMPACT_PERIODIC_KEY_PREFIX + "trigger_time"
MANUAL_COMPACT_ONCE_KEY_PREFIX = MANUAL_COMPACT_KEY_PREFIX + "once."
MANUAL_COMPACT_ONCE_TRIGGER_TIME_KEY = MANUAL_COMPACT_ONCE_KEY_PREFIX + "trigger_time"

MANUAL_COMPACT_TARGET_LEVEL_KEY = "target_level"
MANUAL_COMPACT_BOTTOMMOST_LEVEL_COMPACTION_KEY = "bottommost_level_compaction"
MANUAL_COMPACT_BOTTOMMOST_LEVEL_COMPACTION_FORCE = "force"
MANUAL_COMPACT_BOTTOMMOST_LEVEL_COMPACTION_SKIP = "skip"

# engine-selection env, specific to the TPU rebuild: "cpu" or "tpu"
COMPACTION_BACKEND_KEY = "compaction_backend"

TABLE_LEVEL_DEFAULT_TTL = "default_ttl"

CHECKPOINT_RESERVE_MIN_COUNT = "rocksdb.checkpoint.reserve_min_count"
CHECKPOINT_RESERVE_TIME_SECONDS = "rocksdb.checkpoint.reserve_time_seconds"

PEGASUS_CLUSTER_SECTION_NAME = "pegasus.clusters"

ENV_SLOW_QUERY_THRESHOLD = "replica.slow_query_threshold"
ITERATION_THRESHOLD_TIME_MS = "replica.rocksdb_iteration_threshold_time_ms"
SPLIT_VALIDATE_PARTITION_HASH = "replica.split.validate_partition_hash"
USER_SPECIFIED_COMPACTION = "user_specified_compaction"

# partition-split ownership mask, spread post-split so compaction GCs keys
# the partition no longer owns (reference set_partition_version)
REPLICA_PARTITION_VERSION = "replica.partition_version"

# per-table SST compression (the rocksdb compression_type knob)
ROCKSDB_COMPRESSION_TYPE = "rocksdb.compression_type"

# range-read limiter thresholds (src/server/range_read_limiter.h flags)
ROCKSDB_ITERATION_THRESHOLD_COUNT = "replica.rocksdb_max_iteration_count"
ROCKSDB_ITERATION_THRESHOLD_SIZE = "replica.rocksdb_max_iteration_size"
ROCKSDB_ITERATION_THRESHOLD_TIME_MS = ITERATION_THRESHOLD_TIME_MS

# duplication config travels to replicas as a reserved app-env (the meta
# pushes it with the normal env spread; replicas reconcile duplicators)
ENV_DUPLICATION_KEY = "__duplication__"

# abnormal-size read tracing thresholds (reference _abnormal_* gflags,
# pegasus_server_impl.h:317-343); hot-applied app-envs here, 0 = disabled
ENV_READ_THROTTLING = "replica.read_throttling"
ENV_WRITE_THROTTLING = "replica.write_throttling"
ENV_WRITE_THROTTLING_BY_SIZE = "replica.write_throttling_by_size"
ENV_ABNORMAL_GET_SIZE = "replica.abnormal_get_size_threshold"
ENV_ABNORMAL_MULTI_GET_SIZE = "replica.abnormal_multi_get_size_threshold"
ENV_ABNORMAL_MULTI_GET_ITERATE_COUNT = \
    "replica.abnormal_multi_get_iterate_count_threshold"
