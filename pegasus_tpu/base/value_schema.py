"""Pegasus value schemas v0/v1/v2 — byte-identical to the reference formats.

v0 (src/base/pegasus_value_schema.h:164-179):
    value = [expire_ts (uint32 BE)] [user_data]
v1 (src/base/pegasus_value_schema.h:211-232), adds the duplication timetag:
    value = [expire_ts (uint32 BE)] [timetag (uint64 BE)] [user_data]
    timetag = (timestamp_us << 8) | (cluster_id << 1) | deleted_tag
v2 (src/base/value_schema_v2.cpp:65-92), self-describing:
    value = [0x80|2 (uint8)] [expire_ts (uint32 BE)] [timetag (uint64 BE)] [user_data]

expire_ts is seconds since 2016-01-01 UTC (see utils.epoch_begin); 0 = no TTL.
Dispatch (src/base/value_schema_manager.cpp:42-64): first byte & 0x80 set →
per-record version in the low 7 bits (unknown → latest, forward-compat);
otherwise the table-level data_version from the meta store decides.
"""

import struct
from dataclasses import dataclass

TIMESTAMP_MASK = 0xFFFFFFFFFFFFFF  # 56 bits


def generate_timetag(timestamp_us: int, cluster_id: int, deleted_tag: bool) -> int:
    """src/base/pegasus_value_schema.h:43-46."""
    return ((timestamp_us & TIMESTAMP_MASK) << 8) | ((cluster_id & 0x7F) << 1) | int(deleted_tag)


def extract_timestamp_from_timetag(timetag: int) -> int:
    return (timetag >> 8) & TIMESTAMP_MASK


def extract_cluster_id_from_timetag(timetag: int) -> int:
    return (timetag >> 1) & 0x7F


def extract_deleted_from_timetag(timetag: int) -> bool:
    return bool(timetag & 1)


@dataclass
class ValueFields:
    """Decoded value: the typed fields of src/base/value_field.h:24-59."""

    expire_ts: int
    timetag: int  # 0 for v0
    user_data: bytes
    version: int


class ValueSchemaV0:
    VERSION = 0
    HEADER = 4

    def generate_value(self, expire_ts: int, timetag: int, user_data: bytes) -> bytes:
        return struct.pack(">I", expire_ts) + user_data

    def extract_expire_ts(self, value: bytes) -> int:
        return struct.unpack_from(">I", value, 0)[0]

    def extract_timetag(self, value: bytes) -> int:
        return 0

    def extract_user_data(self, value: bytes) -> bytes:
        return value[self.HEADER :]

    def update_expire_ts(self, value: bytes, new_expire_ts: int) -> bytes:
        return struct.pack(">I", new_expire_ts) + value[4:]

    def extract_fields(self, value: bytes) -> ValueFields:
        return ValueFields(self.extract_expire_ts(value), 0, self.extract_user_data(value), 0)


class ValueSchemaV1(ValueSchemaV0):
    VERSION = 1
    HEADER = 12

    def generate_value(self, expire_ts: int, timetag: int, user_data: bytes) -> bytes:
        return struct.pack(">IQ", expire_ts, timetag) + user_data

    def extract_timetag(self, value: bytes) -> int:
        return struct.unpack_from(">Q", value, 4)[0]

    def extract_fields(self, value: bytes) -> ValueFields:
        return ValueFields(
            self.extract_expire_ts(value),
            self.extract_timetag(value),
            self.extract_user_data(value),
            1,
        )


class ValueSchemaV2:
    VERSION = 2
    HEADER = 13

    def generate_value(self, expire_ts: int, timetag: int, user_data: bytes) -> bytes:
        return struct.pack(">BIQ", 0x80 | self.VERSION, expire_ts, timetag) + user_data

    def extract_expire_ts(self, value: bytes) -> int:
        return struct.unpack_from(">I", value, 1)[0]

    def extract_timetag(self, value: bytes) -> int:
        return struct.unpack_from(">Q", value, 5)[0]

    def extract_user_data(self, value: bytes) -> bytes:
        return value[self.HEADER :]

    def update_expire_ts(self, value: bytes, new_expire_ts: int) -> bytes:
        return value[:1] + struct.pack(">I", new_expire_ts) + value[5:]

    def extract_fields(self, value: bytes) -> ValueFields:
        return ValueFields(
            self.extract_expire_ts(value),
            self.extract_timetag(value),
            self.extract_user_data(value),
            2,
        )


SCHEMAS = {0: ValueSchemaV0(), 1: ValueSchemaV1(), 2: ValueSchemaV2()}
LATEST_VERSION = max(SCHEMAS)


class ValueSchemaManager:
    """First-byte dispatch registry (src/base/value_schema_manager.cpp:26-77)."""

    def get_value_schema(self, meta_cf_data_version: int, value: bytes):
        if value and value[0] & 0x80:
            version = value[0] & 0x7F
            # forward-compat: unknown per-record version falls back to latest
            return SCHEMAS.get(version, SCHEMAS[LATEST_VERSION])
        schema = SCHEMAS.get(meta_cf_data_version)
        if schema is None:
            raise ValueError(f"data version({meta_cf_data_version}) in meta cf is not supported")
        return schema

    def get_latest_value_schema(self):
        return SCHEMAS[LATEST_VERSION]


def check_if_ts_expired(epoch_now: int, expire_ts: int) -> bool:
    """src/base/pegasus_value_schema.h:119-122: 0 means no TTL."""
    return 0 < expire_ts <= epoch_now
