from .crc64 import crc64
from .key_schema import (
    generate_key,
    generate_next_bytes,
    restore_key,
    key_hash,
    hash_key_hash,
    check_key_hash,
)
from .value_schema import (
    generate_timetag,
    extract_timestamp_from_timetag,
    ValueSchemaManager,
    SCHEMAS,
)
from . import consts
from .utils import epoch_now, epoch_begin, c_escape_string

__all__ = [
    "crc64",
    "generate_key",
    "generate_next_bytes",
    "restore_key",
    "key_hash",
    "hash_key_hash",
    "check_key_hash",
    "generate_timetag",
    "extract_timestamp_from_timetag",
    "ValueSchemaManager",
    "SCHEMAS",
    "consts",
    "epoch_now",
    "epoch_begin",
    "c_escape_string",
]
