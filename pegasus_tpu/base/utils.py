"""Base utilities (src/base/pegasus_utils.{h,cpp})."""

import os
import time


def enable_compile_cache(repo_root: str = None) -> None:
    """Point jax's persistent compilation cache at <repo>/.jax_cache — the
    sort/merge networks compile per shape-set and this makes every process
    (tests, bench, driver hooks, servers) reuse them."""
    import jax

    root = repo_root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    jax.config.update("jax_compilation_cache_dir", os.path.join(root, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

# TTL timestamps are seconds since 2016-01-01 00:00:00 GMT
# (src/base/pegasus_utils.h:34-36)
epoch_begin = 1451606400


def epoch_now(now: float = None) -> int:
    """Seconds since the 2016 epoch; the expire_ts clock."""
    return int(now if now is not None else time.time()) - epoch_begin


_PRINTABLE = set(range(0x20, 0x7F)) - {ord('"'), ord("\\")}


def c_escape_string(data: bytes, always_escape: bool = False) -> str:
    """C-style escaping for log/shell display (src/base/pegasus_utils.h)."""
    out = []
    for b in data:
        if not always_escape and b in _PRINTABLE:
            out.append(chr(b))
        elif b == ord('"') and not always_escape:
            out.append('\\"')
        elif b == ord("\\") and not always_escape:
            out.append("\\\\")
        else:
            out.append(f"\\x{b:02X}")
    return "".join(out)


def c_unescape_string(s: str) -> bytes:
    """Inverse of c_escape_string for shell input."""
    out = bytearray()
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            n = s[i + 1]
            if n == "x" and i + 3 < len(s):
                out.append(int(s[i + 2 : i + 4], 16))
                i += 4
                continue
            if n in ('"', "\\"):
                out.append(ord(n))
                i += 2
                continue
        out.append(ord(c))
        i += 1
    return bytes(out)
