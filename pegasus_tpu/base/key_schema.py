"""Pegasus key codec — byte-identical to the reference format.

stored key = [hash_key_len (uint16 big-endian)] [hash_key bytes] [sort_key bytes]
(reference: src/base/pegasus_key_schema.h:34-58)

Keys sort byte-lexicographically, so all records of one hash_key are contiguous
and ordered by sort_key; the length prefix makes short hash_keys sort before
longer ones that share a prefix, exactly as the reference engine relies on for
range scans.
"""

import struct

from .crc64 import crc64

UINT16_MAX = 0xFFFF


def generate_key(hash_key: bytes, sort_key: bytes = b"") -> bytes:
    """pegasus_generate_key (src/base/pegasus_key_schema.h:40-58)."""
    if len(hash_key) >= UINT16_MAX:
        raise ValueError("hash key length must be less than UINT16_MAX")
    return struct.pack(">H", len(hash_key)) + hash_key + sort_key


def generate_next_bytes(hash_key: bytes, sort_key: bytes = None) -> bytes:
    """Adjacent successor key for exclusive range stops.

    pegasus_generate_next_blob (src/base/pegasus_key_schema.h:63-97): strip
    trailing 0xFF bytes, then increment the last remaining byte. With sort_key
    None this is the successor of the hash_key prefix (stop for a full
    hash_key scan); with a sort_key it is the successor of the exact key.
    """
    buf = bytearray(generate_key(hash_key, sort_key if sort_key is not None else b""))
    p = len(buf) - 1
    while buf[p] == 0xFF:
        p -= 1
    buf[p] += 1
    return bytes(buf[: p + 1])


def expire_ts_from_ttl(ttl_seconds: int) -> int:
    """TTL seconds -> absolute expire timestamp (2016-based epoch); 0 = none
    (reference: pegasus_value_schema.h expire encoding on the client path)."""
    from .utils import epoch_now

    return epoch_now() + int(ttl_seconds) if ttl_seconds > 0 else 0


def restore_key(key: bytes) -> tuple:
    """(hash_key, sort_key) from a stored key (src/base/pegasus_key_schema.h:101-122)."""
    if len(key) < 2:
        raise ValueError("key length must be no less than 2")
    (hash_key_len,) = struct.unpack_from(">H", key, 0)
    if len(key) < 2 + hash_key_len:
        raise ValueError("key length must be no less than (2 + hash_key_len)")
    return key[2 : 2 + hash_key_len], key[2 + hash_key_len :]


def key_hash(key: bytes) -> int:
    """Partition hash from a stored key (src/base/pegasus_key_schema.h:151-167).

    hash_key_len > 0: crc64 of the hash_key; == 0: crc64 of the sort_key —
    so sort_key-only tables still spread across partitions.
    """
    if len(key) < 2:
        raise ValueError("key length must be no less than 2")
    (hash_key_len,) = struct.unpack_from(">H", key, 0)
    if hash_key_len > 0:
        if len(key) < 2 + hash_key_len:
            raise ValueError("key length must be no less than (2 + hash_key_len)")
        return crc64(key[2 : 2 + hash_key_len])
    return crc64(key[2:])


def hash_key_hash(hash_key: bytes) -> int:
    """pegasus_hash_key_hash (src/base/pegasus_key_schema.h:170-173)."""
    return crc64(hash_key)


def check_key_hash(key: bytes, pidx: int, partition_version: int) -> bool:
    """True iff this key is served by partition `pidx` under `partition_version`
    (a 2^k-1 mask during/after split; src/base/pegasus_key_schema.h:178-185)."""
    return (key_hash(key) & partition_version) == pidx
