"""CRC-64 used for partition hashing.

The reference computes partition hashes with rDSN's ``dsn::utils::crc64_calc``
(consumed at src/base/pegasus_key_schema.h:162,172); the rdsn submodule is not
checked out, so the exact polynomial is unverifiable in-tree. We use the
well-documented CRC-64/XZ parameters (reflected poly 0xC96C5795D7870F42,
init/xorout 0xFFFFFFFFFFFFFFFF folded into an incremental API that matches
``crc64_calc(data, len, initial)`` call shape). The hash only has to be
self-consistent across our client/server/engine: it decides partition routing
(hash & (partition_count-1)) and split-era ownership checks.

A vectorized numpy variant is provided so KV-block encoders can hash entire
batches of hash_keys without a Python loop.
"""

import numpy as np

_POLY = 0xC96C5795D7870F42

def _make_table() -> np.ndarray:
    table = np.zeros(256, dtype=np.uint64)
    for i in range(256):
        crc = i
        for _ in range(8):
            if crc & 1:
                crc = (crc >> 1) ^ _POLY
            else:
                crc >>= 1
        table[i] = crc
    return table

_TABLE = _make_table()
_TABLE_LIST = _TABLE.tolist()  # python ints: faster in the scalar loop
_MASK = 0xFFFFFFFFFFFFFFFF


def crc64(data: bytes, initial: int = 0) -> int:
    """crc64_calc(data, len, initial) equivalent (src/base/pegasus_key_schema.h:162)."""
    crc = (initial ^ _MASK) & _MASK
    tbl = _TABLE_LIST
    for b in data:
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return (crc ^ _MASK) & _MASK


def crc64_batch(arena: np.ndarray, offsets: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Hash many byte strings packed in one uint8 arena.

    arena: uint8[total]; offsets/lengths: int64[n]. Returns uint64[n].
    Uses the native slice-by-8 kernel (pegasus_tpu.native) when the
    toolchain is available, else the vectorized numpy path below.
    """
    from .. import native

    if native.available():
        return native.crc64_batch(arena, offsets, lengths)
    return crc64_batch_numpy(arena, offsets, lengths)


def crc64_batch_numpy(arena: np.ndarray, offsets: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    """Numpy fallback: vectorized across records byte-position-at-a-time;
    iteration count is max(lengths), each step processes every record still
    live. Hash keys are short, so this beats a Python loop by ~100x."""
    n = len(offsets)
    crc = np.full(n, _MASK, dtype=np.uint64)
    if n == 0:
        return crc
    maxlen = int(lengths.max()) if n else 0
    offsets = offsets.astype(np.int64)
    lengths = lengths.astype(np.int64)
    for i in range(maxlen):
        live = lengths > i
        if not live.any():
            break
        idx = offsets[live] + i
        b = arena[idx].astype(np.uint64)
        c = crc[live]
        crc[live] = _TABLE[((c ^ b) & np.uint64(0xFF)).astype(np.int64)] ^ (c >> np.uint64(8))
    return crc ^ np.uint64(_MASK)
