"""pegasus_bench equivalent: fillrandom + full compaction, cpu vs tpu backend.

Mirrors the reference harness shape (src/test/bench_test: fillrandom_pegasus
then manual compact; BASELINE.json north star = fillrandom+compact wall-clock
vs CPU) on this build's engine: generate N records across K overlapping runs,
flush-sort each run (an L0 state — untimed, as in the reference where bench
fills then separately times manual_compact), then run the full
merge+dedup+TTL-filter compaction on both backends:

  cpu: vectorized numpy k-way merge (searchsorted ranks over memcmp-ordered
       packed keys — a strong CPU implementation, deliberately NOT the slow
       lexsort strawman; stand-in for CPU RocksDB until the C++ harness lands)
  tpu: JAX bitonic-merge networks on the real chip. Key columns are
       device-resident (uploaded at flush, the engine's architecture), so the
       timed path is kernel + survivor-index download + host arena gather.

Both lanes share the packing (flush artifact) and are timed from merge start
to fully materialized output block; outputs are asserted BYTE-IDENTICAL.

Prints ONE json line:
  {"metric": ..., "value": speedup, "unit": "x", "vs_baseline": ...}
vs_baseline is speedup / 1.0 (the CPU path IS the measured baseline; the
reference publishes no in-repo numbers — BASELINE.md).

Env knobs: PEGASUS_BENCH_N (records, default 10_000_000), PEGASUS_BENCH_VALUE
(user bytes per value, default 100), PEGASUS_BENCH_RUNS (L0 runs, default 4),
PEGASUS_BENCH_REPS (timed reps, default 3).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

_RESULT_PRINTED = False


def _emit(result: dict) -> None:
    global _RESULT_PRINTED
    # flag BEFORE printing: the watchdog thread check-then-emits on it, and
    # the reverse order could produce two conflicting JSON lines
    _RESULT_PRINTED = True
    print(json.dumps(result), flush=True)


def _bench_params():
    """(n_total, n_runs, value_size, reps) — single source for main(), the
    watchdog, and the crash handler so the degraded line's metric name
    always matches the success path's."""
    return (int(os.environ.get("PEGASUS_BENCH_N", 10_000_000)),
            int(os.environ.get("PEGASUS_BENCH_RUNS", 4)),
            int(os.environ.get("PEGASUS_BENCH_VALUE", 100)),
            int(os.environ.get("PEGASUS_BENCH_REPS", 3)))


def _metric_name(n_total, n_runs, value_size) -> str:
    return ("fillrandom+compact: tpu-backend compaction speedup vs cpu "
            f"backend ({n_total} records, {n_runs} runs, value={value_size}B)")


def _degraded(n_total, n_runs, value_size, reason, detail=None) -> dict:
    """The JSON line for a bench that could not produce a speedup: still
    parseable (BENCH_r02 recorded nothing because backend-init death
    stack-traced straight past the print)."""
    d = {"tpu_unavailable": True, "reason": reason}
    d.update(detail or {})
    return {"metric": _metric_name(n_total, n_runs, value_size),
            "value": None, "unit": "x", "vs_baseline": None, "detail": d}


def _probe_backend(timeout_s=None):
    """-> (ok, platform_or_reason). Initializes the jax backend in a
    time-bounded SUBPROCESS: a wedged axon tunnel blocks device init
    forever in-process (watchdog can't help: the hang is in a C++ retry
    loop), and a killed probe child doesn't take the bench down."""
    if os.environ.get("PEGASUS_BENCH_ASSUME_TPU") == "1":
        # in-process caller (tools/tpu_oneshot.py) already holds a live
        # backend session; a subprocess probe would contend for the single
        # device lease and false-negative
        import jax

        return True, str(jax.devices()[0])
    timeout_s = timeout_s or float(os.environ.get("PEGASUS_BENCH_PROBE_S", 150))
    code = ("import jax\n"
            "import os\n"
            "if os.environ.get('JAX_PLATFORMS') == 'cpu':\n"
            "    jax.config.update('jax_platforms', 'cpu')\n"
            "d = jax.devices()\n"
            "import jax.numpy as jnp\n"
            "assert int(jnp.arange(4).sum()) == 6\n"
            "print('PLATFORM:', d[0])\n")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout_s,
                              cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        return False, (f"backend init exceeded {timeout_s:.0f}s "
                       "(device tunnel wedged)")
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return False, "backend init failed: " + " | ".join(tail)[-400:]
    for line in (proc.stdout or "").splitlines():
        if line.startswith("PLATFORM: "):
            return True, line[len("PLATFORM: "):]
    return False, "backend probe produced no platform line"


def _enable_compile_cache():
    import jax

    from pegasus_tpu.base.utils import enable_compile_cache

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the image re-asserts the axon platform over the env var; the
        # config API wins over both (matches tests/conftest + dryrun)
        jax.config.update("jax_platforms", "cpu")
    enable_compile_cache(os.path.dirname(os.path.abspath(__file__)))


def make_run(n: int, value_size: int, seed: int, key_space: int) -> "KVBlock":
    """Vectorized fillrandom: n records, 16B hashkey + 8B sortkey, v2 values,
    ~10% with TTL already expired, ~5% tombstones (fractions overridable:
    PEGASUS_BENCH_TTL_FRAC / PEGASUS_BENCH_DEL_FRAC — the TTL-expiring
    compaction scenario of BASELINE.json is TTL_FRAC=0.5+)."""
    from pegasus_tpu.engine.block import KVBlock

    ttl_frac = float(os.environ.get("PEGASUS_BENCH_TTL_FRAC", 0.10))
    del_frac = float(os.environ.get("PEGASUS_BENCH_DEL_FRAC", 0.05))

    rng = np.random.default_rng(seed)
    klen = 2 + 16 + 8
    keys = np.zeros((n, klen), dtype=np.uint8)
    keys[:, 0], keys[:, 1] = 0, 16  # u16 BE hashkey len
    # hashkeys drawn from a bounded space so runs overlap (dedup work exists)
    hk_ids = rng.integers(0, key_space, size=n)
    digits = np.zeros((n, 16), np.uint8)
    v = hk_ids.copy()
    for j in range(15, 7, -1):
        digits[:, j] = 48 + (v % 10)
        v //= 10
    digits[:, :8] = np.frombuffer(b"userhash", dtype=np.uint8)
    keys[:, 2:18] = digits
    keys[:, 18:26] = rng.integers(0, 256, size=(n, 8), dtype=np.uint8)

    vlen = 13 + value_size  # v2 header + payload
    vals = rng.integers(0, 256, size=(n, vlen), dtype=np.uint8)
    vals[:, 0] = 0x82
    expire = np.zeros(n, np.uint32)
    with_ttl = rng.random(n) < ttl_frac
    expire[with_ttl] = rng.integers(1, 50, size=int(with_ttl.sum()), dtype=np.uint32)
    vals[:, 1] = (expire >> 24).astype(np.uint8)
    vals[:, 2] = (expire >> 16).astype(np.uint8)
    vals[:, 3] = (expire >> 8).astype(np.uint8)
    vals[:, 4] = expire.astype(np.uint8)
    vals[:, 5:13] = 0
    deleted = rng.random(n) < del_frac

    from pegasus_tpu.base.crc64 import crc64_batch

    hashes = crc64_batch(keys.reshape(-1), np.arange(n, dtype=np.int64) * klen + 2,
                         np.full(n, 16, np.int64))
    return KVBlock(
        key_arena=keys.reshape(-1),
        key_off=np.arange(n, dtype=np.int64) * klen,
        key_len=np.full(n, klen, np.int32),
        val_arena=vals.reshape(-1),
        val_off=np.arange(n, dtype=np.int64) * vlen,
        val_len=np.full(n, vlen, np.int32),
        expire_ts=expire,
        hash32=(hashes & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        deleted=deleted,
    )


def presort_run(block):
    """Flush: order the raw fill by key (untimed; L0 SSTs are born sorted)."""
    from pegasus_tpu.ops.packing import pack_key_prefixes, pack_sbytes

    w = 7  # 26-byte keys -> ceil(26/4)
    pref = pack_key_prefixes(block.key_arena, block.key_off, block.key_len, w)
    sb = pack_sbytes([pref[:, j] for j in range(w)],
                     block.key_len.astype(np.uint32))
    order = np.argsort(sb, kind="stable")
    # drop within-run duplicate keys (LSM invariant; first writer wins)
    sb_sorted = sb[order]
    uniq = np.ones(len(order), dtype=bool)
    uniq[1:] = sb_sorted[1:] != sb_sorted[:-1]
    return block.gather(order[uniq])


def _arm_watchdog():
    """The TPU tunnel can wedge (device-lease retry sleeps forever); a hung
    bench is worse than a failed one for the driver. Hard-exit with a
    diagnostic after PEGASUS_BENCH_TIMEOUT_S (0 disables)."""
    import threading

    budget = int(os.environ.get("PEGASUS_BENCH_TIMEOUT_S", 2400))
    if budget <= 0:
        return

    def boom():
        print(f"bench watchdog: no result after {budget}s — the TPU device "
              f"tunnel is likely wedged (device-lease retry loop; observed "
              f"after clients are killed mid-run). Last recorded measurements "
              f"are in BASELINE.md.", file=sys.stderr, flush=True)
        if not _RESULT_PRINTED:
            # still hand the driver a parseable line before dying
            n_total, n_runs, value_size, _ = _bench_params()
            _emit(_degraded(n_total, n_runs, value_size,
                            f"watchdog fired after {budget}s (likely wedged "
                            "mid-run after a healthy probe)"))
        os._exit(3)

    t = threading.Timer(budget, boom)
    t.daemon = True
    t.start()


def main():
    _arm_watchdog()
    n_total, n_runs, value_size, reps = _bench_params()

    # 1) bounded backend probe BEFORE anything touches jax in-process
    tpu_ok, platform = _probe_backend()
    if not tpu_ok:
        print(f"bench: TPU backend unavailable ({platform}); running the "
              "cpu lane only and reporting a degraded result.",
              file=sys.stderr, flush=True)

    # 2) fill + pack (pure numpy; shared by both lanes, untimed)
    from pegasus_tpu.engine.block import KVBlock
    from pegasus_tpu.ops.compact import (CompactOptions, CpuBackend, TpuBackend,
                                         pack_runs)

    t0 = time.perf_counter()
    per = n_total // n_runs
    runs = [presort_run(make_run(per, value_size, seed=s,
                                 key_space=max(1, n_total // 2)))
            for s in range(n_runs)]
    opts = CompactOptions(backend="tpu", now=100, bottommost=True,
                          runs_sorted=True)
    packed = pack_runs(runs, opts, need_sbytes=True)
    concat = KVBlock.concat(runs)
    fill_s = time.perf_counter() - t0
    n_in = sum(packed.lens)
    fargs = (opts.now, opts.pidx, opts.partition_mask, True, True)

    def lane(backend, packed_in):
        from pegasus_tpu.ops.compact import gather_device_survivors

        best, out, split = float("inf"), None, {}
        for _ in range(reps + 1):  # first rep is warmup (jit compile)
            t0 = time.perf_counter()
            if hasattr(backend, "survivors_device"):
                dev_idx, cnt = backend.survivors_device(packed_in, *fargs)
                t1 = time.perf_counter()
                # index download overlaps the memcpy-bound arena gather
                out = gather_device_survivors(concat, dev_idx, cnt)
            else:
                surv = backend.survivors(packed_in, *fargs)
                t1 = time.perf_counter()
                out = concat.gather(surv)
            total = time.perf_counter() - t0
            if total < best:
                best = total
                split = {"merge_s": round(t1 - t0, 3),
                         "gather_s": round(total - (t1 - t0), 3)}
        return best, out, split

    cpu_s, cpu_out, cpu_split = lane(CpuBackend(), packed)

    if not tpu_ok:
        _emit(_degraded(n_total, n_runs, value_size, platform, detail={
            "fill_s": round(fill_s, 3),
            "cpu_compact_s": round(cpu_s, 3),
            "cpu_records_per_s": int(n_in / cpu_s),
            "input_records": n_in,
            "output_records": int(cpu_out.n),
        }))
        return

    # 3) TPU lane (device residency prepared at "flush time": untimed)
    _enable_compile_cache()
    tpu_backend = TpuBackend()
    prep = tpu_backend.prepare(packed)
    tpu_s, tpu_out, tpu_split = lane(tpu_backend, prep)

    assert cpu_out.n == tpu_out.n, "backend outputs diverge in count"
    assert np.array_equal(cpu_out.key_arena, tpu_out.key_arena), "key bytes diverge"
    assert np.array_equal(cpu_out.val_arena, tpu_out.val_arena), "value bytes diverge"

    speedup = cpu_s / tpu_s
    _emit({
        "metric": _metric_name(n_total, n_runs, value_size),
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup, 3),
        "detail": {
            "fill_s": round(fill_s, 3),
            "cpu_compact_s": round(cpu_s, 3),
            "cpu_split": cpu_split,
            "tpu_compact_s": round(tpu_s, 3),
            "tpu_split": tpu_split,
            "tpu_records_per_s": int(n_in / tpu_s),
            "input_records": n_in,
            "output_records": int(tpu_out.n),
            "byte_equal": True,
            "platform": platform,
        },
    })


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 - the driver needs a JSON line, always
        import traceback

        traceback.print_exc()
        if not _RESULT_PRINTED:
            n_total, n_runs, value_size, _ = _bench_params()
            _emit(_degraded(n_total, n_runs, value_size,
                            f"bench crashed: {e!r}"))
        sys.exit(0 if _RESULT_PRINTED else 1)
