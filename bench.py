"""pegasus_bench equivalent: fillrandom + full compaction, cpu vs tpu backend.

Mirrors the reference harness shape (src/test/bench_test: fillrandom_pegasus
then manual compact; BASELINE.json north star = fillrandom+compact wall-clock
vs CPU) on this build's engine: generate N records across K overlapping runs
(an L0 state), then run the full merge+dedup+TTL-filter compaction on the CPU
backend (vectorized numpy — the stand-in for CPU RocksDB's compaction until
the C++ harness lands) and on the TPU backend (JAX kernels on the real chip).

Prints ONE json line:
  {"metric": ..., "value": speedup, "unit": "x", "vs_baseline": ...}
vs_baseline is speedup / 1.0 (the CPU path IS the measured baseline; the
reference publishes no in-repo numbers — BASELINE.md).

Env knobs: PEGASUS_BENCH_N (records, default 2_000_000), PEGASUS_BENCH_VALUE
(user bytes per value, default 100), PEGASUS_BENCH_RUNS (L0 runs, default 4),
PEGASUS_BENCH_REPS (timed reps, default 3).
"""

import json
import os
import sys
import time

import numpy as np


def make_run(n: int, value_size: int, seed: int, key_space: int) -> "KVBlock":
    """Vectorized fillrandom: n records, 16B hashkey + 8B sortkey, v2 values,
    ~10% with TTL already expired, ~5% tombstones."""
    from pegasus_tpu.engine.block import KVBlock

    rng = np.random.default_rng(seed)
    klen = 2 + 16 + 8
    keys = np.zeros((n, klen), dtype=np.uint8)
    keys[:, 0], keys[:, 1] = 0, 16  # u16 BE hashkey len
    # hashkeys drawn from a bounded space so runs overlap (dedup work exists)
    hk_ids = rng.integers(0, key_space, size=n)
    digits = np.zeros((n, 16), np.uint8)
    v = hk_ids.copy()
    for j in range(15, 7, -1):
        digits[:, j] = 48 + (v % 10)
        v //= 10
    digits[:, :8] = np.frombuffer(b"userhash", dtype=np.uint8)
    keys[:, 2:18] = digits
    keys[:, 18:26] = rng.integers(0, 256, size=(n, 8), dtype=np.uint8)

    vlen = 13 + value_size  # v2 header + payload
    vals = rng.integers(0, 256, size=(n, vlen), dtype=np.uint8)
    vals[:, 0] = 0x82
    expire = np.zeros(n, np.uint32)
    with_ttl = rng.random(n) < 0.10
    expire[with_ttl] = rng.integers(1, 50, size=int(with_ttl.sum()), dtype=np.uint32)
    vals[:, 1] = (expire >> 24).astype(np.uint8)
    vals[:, 2] = (expire >> 16).astype(np.uint8)
    vals[:, 3] = (expire >> 8).astype(np.uint8)
    vals[:, 4] = expire.astype(np.uint8)
    vals[:, 5:13] = 0
    deleted = rng.random(n) < 0.05

    from pegasus_tpu.base.crc64 import crc64_batch

    hashes = crc64_batch(keys.reshape(-1), np.arange(n, dtype=np.int64) * klen + 2,
                         np.full(n, 16, np.int64))
    return KVBlock(
        key_arena=keys.reshape(-1),
        key_off=np.arange(n, dtype=np.int64) * klen,
        key_len=np.full(n, klen, np.int32),
        val_arena=vals.reshape(-1),
        val_off=np.arange(n, dtype=np.int64) * vlen,
        val_len=np.full(n, vlen, np.int32),
        expire_ts=expire,
        hash32=(hashes & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        deleted=deleted,
    )


def time_backend(runs, backend: str, reps: int) -> tuple:
    from pegasus_tpu.ops.compact import CompactOptions, compact_blocks

    opts = CompactOptions(backend=backend, now=100, bottommost=True)
    # warmup (jit compile for tpu; page-in for cpu)
    out = compact_blocks(runs, opts)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = compact_blocks(runs, opts)
        best = min(best, time.perf_counter() - t0)
    return best, out


def main():
    n_total = int(os.environ.get("PEGASUS_BENCH_N", 2_000_000))
    value_size = int(os.environ.get("PEGASUS_BENCH_VALUE", 100))
    n_runs = int(os.environ.get("PEGASUS_BENCH_RUNS", 4))
    reps = int(os.environ.get("PEGASUS_BENCH_REPS", 3))

    t0 = time.perf_counter()
    per = n_total // n_runs
    runs = [make_run(per, value_size, seed=s, key_space=max(1, n_total // 2))
            for s in range(n_runs)]
    fill_s = time.perf_counter() - t0

    cpu_s, cpu_out = time_backend(runs, "cpu", reps)
    tpu_s, tpu_out = time_backend(runs, "tpu", reps)
    assert cpu_out.block.n == tpu_out.block.n, "backend outputs diverge"

    speedup = cpu_s / tpu_s
    recs_per_s = n_total / tpu_s
    result = {
        "metric": "fillrandom+compact: tpu-backend compaction speedup vs cpu backend "
                  f"({n_total} records, {n_runs} runs, value={value_size}B)",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup, 3),
        "detail": {
            "fill_s": round(fill_s, 3),
            "cpu_compact_s": round(cpu_s, 3),
            "tpu_compact_s": round(tpu_s, 3),
            "tpu_records_per_s": int(recs_per_s),
            "output_records": int(tpu_out.block.n),
            "platform": _platform(),
        },
    }
    print(json.dumps(result))


def _platform() -> str:
    import jax

    return str(jax.devices()[0])


if __name__ == "__main__":
    main()
