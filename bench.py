"""pegasus_bench equivalent: fillrandom + full compaction, cpu vs tpu backend.

Mirrors the reference harness shape (src/test/bench_test: fillrandom_pegasus
then manual compact; BASELINE.json north star = fillrandom+compact wall-clock
vs CPU) on this build's engine: generate N records across K overlapping runs,
flush-sort each run (an L0 state — untimed, as in the reference where bench
fills then separately times manual_compact), then run the full
merge+dedup+TTL-filter compaction on both backends:

  cpu: vectorized numpy k-way merge (searchsorted ranks over memcmp-ordered
       packed keys — a strong CPU implementation, deliberately NOT the slow
       lexsort strawman; stand-in for CPU RocksDB until the C++ harness lands)
  tpu: JAX bitonic-merge networks on the real chip. Key columns are
       device-resident (uploaded at flush, the engine's architecture), so the
       timed path is kernel + survivor materialization (device value gather
       overlapped with host key gather, or the host fused gather — whichever
       this box measures faster).

Both lanes share the fill recipe (seed-deterministic) and are timed from
merge start to fully materialized output block; outputs are asserted
BYTE-IDENTICAL (sha256 across the process boundary).

Process architecture (why the TPU lane is a separate bounded child):
the axon tunnel hands out ONE device lease and does not always release
it when a client exits (observed r3: first client in wins, later inits
sleep forever in the plugin's C++ retry loop — unkillable by an
in-process watchdog). So the parent NEVER imports jax; one child does
backend init + the whole TPU lane under a parent-enforced deadline, with
stdout/stderr on files (an abandoned child must not hold the driver's
pipes open). On timeout the child gets SIGTERM + grace; if it ignores
that it is ABANDONED, never SIGKILLed (killing a TPU-attached process
wedges the tunnel lease for hours). The parent then emits the degraded
JSON line WITH the CPU lane's numbers, rc=0. Worst case wall-clock is
fill+cpu (~2 min at 10M) + PEGASUS_BENCH_LANE_S, under the 600 s
watchdog, under the driver budget.

Prints ONE json line:
  {"metric": ..., "value": speedup, "unit": "x", "vs_baseline": ...}
vs_baseline is speedup / 1.0 (the CPU path IS the measured baseline; the
reference publishes no in-repo numbers — BASELINE.md).

Env knobs: PEGASUS_BENCH_N (records, default 10_000_000), PEGASUS_BENCH_VALUE
(user bytes per value, default 100), PEGASUS_BENCH_RUNS (L0 runs, default 4),
PEGASUS_BENCH_REPS (timed reps, default 3), PEGASUS_BENCH_LANE_S (TPU child
deadline, default 360), PEGASUS_BENCH_DEADLINE_S (in-process per-attempt
lane-guard deadline, default 0.7 * LANE_S so the stage-attributed abandon
undercuts the external kill), PEGASUS_BENCH_TIMEOUT_S (whole-bench
watchdog, default 600).
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

_RESULT_PRINTED = False
# watchdog visibility: measured CPU numbers (so a backstop line still
# carries them) and the live lane child (so the backstop can SIGTERM it
# instead of leaking a process past the parent's exit)
_CPU_DETAIL = None
_LANE_STATE = {"proc": None, "files": []}


def _emit(result: dict) -> None:
    global _RESULT_PRINTED
    # flag BEFORE printing: the watchdog thread check-then-emits on it, and
    # the reverse order could produce two conflicting JSON lines
    _RESULT_PRINTED = True
    print(json.dumps(result), flush=True)


def _host_info() -> dict:
    """Host-contention attribution (VERDICT-r5: a 2.9s -> 7.8s CPU-lane
    regression was only guessable as host contention): loadavg + core
    count recorded in every BENCH detail; the per-stage process_time vs
    wall split rides in the trace summaries (runtime/tracing.py cpu_s —
    cpu_s >> s means parallel threads worked under the span, s >> cpu_s
    with high loadavg means the host starved the stage)."""
    try:
        la = [round(x, 2) for x in os.getloadavg()]
    except (AttributeError, OSError):
        la = None
    return {"cpu_count": os.cpu_count(), "loadavg": la}


def _bench_params():
    """(n_total, n_runs, value_size, reps) — single source for main(), the
    child lane, the watchdog, and the crash handler so the degraded line's
    metric name always matches the success path's."""
    return (int(os.environ.get("PEGASUS_BENCH_N", 10_000_000)),
            int(os.environ.get("PEGASUS_BENCH_RUNS", 4)),
            int(os.environ.get("PEGASUS_BENCH_VALUE", 100)),
            int(os.environ.get("PEGASUS_BENCH_REPS", 3)))


def _metric_name(n_total, n_runs, value_size) -> str:
    return ("fillrandom+compact: tpu-backend compaction speedup vs cpu "
            f"backend ({n_total} records, {n_runs} runs, value={value_size}B)")


def _degraded(n_total, n_runs, value_size, reason, detail=None) -> dict:
    """The JSON line for a bench that could not produce a speedup: still
    parseable (BENCH_r02 recorded nothing because backend-init death
    stack-traced straight past the print; BENCH_r03 recorded nothing
    because a post-probe wedge outlived the driver budget)."""
    d = {"tpu_unavailable": True, "reason": reason}
    d.update(detail or {})
    return {"metric": _metric_name(n_total, n_runs, value_size),
            "value": None, "unit": "x", "vs_baseline": None, "detail": d}


def _enable_compile_cache():
    import jax

    from pegasus_tpu.base.utils import enable_compile_cache

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the image re-asserts the axon platform over the env var; the
        # config API wins over both (matches tests/conftest + dryrun)
        jax.config.update("jax_platforms", "cpu")
    enable_compile_cache(os.path.dirname(os.path.abspath(__file__)))


def make_run(n: int, value_size: int, seed: int, key_space: int) -> "KVBlock":
    """Vectorized fillrandom: n records, 16B hashkey + 8B sortkey, v2 values,
    ~10% with TTL already expired, ~5% tombstones (fractions overridable:
    PEGASUS_BENCH_TTL_FRAC / PEGASUS_BENCH_DEL_FRAC — the TTL-expiring
    compaction scenario of BASELINE.json is TTL_FRAC=0.5+). Seed-deterministic:
    the TPU child regenerates the identical fill from the same seeds."""
    from pegasus_tpu.engine.block import KVBlock

    ttl_frac = float(os.environ.get("PEGASUS_BENCH_TTL_FRAC", 0.10))
    del_frac = float(os.environ.get("PEGASUS_BENCH_DEL_FRAC", 0.05))

    rng = np.random.default_rng(seed)
    klen = 2 + 16 + 8
    keys = np.zeros((n, klen), dtype=np.uint8)
    keys[:, 0], keys[:, 1] = 0, 16  # u16 BE hashkey len
    # hashkeys drawn from a bounded space so runs overlap (dedup work exists)
    hk_ids = rng.integers(0, key_space, size=n)
    digits = np.zeros((n, 16), np.uint8)
    v = hk_ids.copy()
    for j in range(15, 7, -1):
        digits[:, j] = 48 + (v % 10)
        v //= 10
    digits[:, :8] = np.frombuffer(b"userhash", dtype=np.uint8)
    keys[:, 2:18] = digits
    keys[:, 18:26] = rng.integers(0, 256, size=(n, 8), dtype=np.uint8)

    vlen = 13 + value_size  # v2 header + payload
    vals = rng.integers(0, 256, size=(n, vlen), dtype=np.uint8)
    vals[:, 0] = 0x82
    expire = np.zeros(n, np.uint32)
    with_ttl = rng.random(n) < ttl_frac
    expire[with_ttl] = rng.integers(1, 50, size=int(with_ttl.sum()), dtype=np.uint32)
    vals[:, 1] = (expire >> 24).astype(np.uint8)
    vals[:, 2] = (expire >> 16).astype(np.uint8)
    vals[:, 3] = (expire >> 8).astype(np.uint8)
    vals[:, 4] = expire.astype(np.uint8)
    vals[:, 5:13] = 0
    deleted = rng.random(n) < del_frac

    from pegasus_tpu.base.crc64 import crc64_batch

    hashes = crc64_batch(keys.reshape(-1), np.arange(n, dtype=np.int64) * klen + 2,
                         np.full(n, 16, np.int64))
    return KVBlock(
        key_arena=keys.reshape(-1),
        key_off=np.arange(n, dtype=np.int64) * klen,
        key_len=np.full(n, klen, np.int32),
        val_arena=vals.reshape(-1),
        val_off=np.arange(n, dtype=np.int64) * vlen,
        val_len=np.full(n, vlen, np.int32),
        expire_ts=expire,
        hash32=(hashes & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        deleted=deleted,
    )


def presort_run(block):
    """Flush: order the raw fill by key (untimed; L0 SSTs are born sorted)."""
    from pegasus_tpu.ops.packing import pack_key_prefixes, pack_sbytes

    w = 7  # 26-byte keys -> ceil(26/4)
    pref = pack_key_prefixes(block.key_arena, block.key_off, block.key_len, w)
    sb = pack_sbytes([pref[:, j] for j in range(w)],
                     block.key_len.astype(np.uint32))
    order = np.argsort(sb, kind="stable")
    # drop within-run duplicate keys (LSM invariant; first writer wins)
    sb_sorted = sb[order]
    uniq = np.ones(len(order), dtype=bool)
    uniq[1:] = sb_sorted[1:] != sb_sorted[:-1]
    return block.gather(order[uniq])


def _fill(n_total, n_runs, value_size):
    """-> (runs, fill_s). Shared verbatim by parent (CPU lane) and the TPU
    child; determinism across the two processes is what lets byte equality
    be checked by hash."""
    t0 = time.perf_counter()
    runs = [presort_run(make_run(n_total // n_runs, value_size, seed=s,
                                 key_space=max(1, n_total // 2)))
            for s in range(n_runs)]
    return runs, time.perf_counter() - t0


def _out_digest(block) -> dict:
    return {
        "n_out": int(block.n),
        "key_sha": hashlib.sha256(block.key_arena).hexdigest(),
        "val_sha": hashlib.sha256(block.val_arena).hexdigest(),
    }


def _lane_deadline_s() -> float:
    """Per-attempt in-process deadline for the guarded device lane. It
    must undercut PEGASUS_BENCH_LANE_S by a real margin: the parent's
    timer covers the whole child lifetime (init + fill + prep too), so an
    equal deadline would always lose the race to the external SIGTERM and
    the stage-attributed abandon would never fire. The parent kill stays
    the backstop for wedges outside the guarded merge itself."""
    v = os.environ.get("PEGASUS_BENCH_DEADLINE_S")
    if v:
        return float(v)
    # strictly under lane_s even for tiny operator-set budgets, or the
    # external SIGTERM always wins and this deadline is dead code
    lane_s = float(os.environ.get("PEGASUS_BENCH_LANE_S", 360))
    return max(5.0, min(lane_s * 0.7, lane_s - 10.0))


def _lane(backend, packed_in, concat, fargs, reps, dev_vals=None):
    """Timed compaction lane: merge + survivor materialization, best of
    reps (first rep is jit-compile warmup). dev_vals switches the device
    lane's materialization to HBM-resident value rows (downloaded as one
    block, overlapped with the host key gather).

    The device lane runs under the lane guard with fallback DISABLED: a
    bench must report the device number or fail loudly — a silent cpu
    fallback would publish a cpu time as "tpu". Retries/abandons land in
    the guard's counters, exported as the JSON line's detail.lane."""
    from pegasus_tpu.ops.compact import (gather_device_survivors,
                                         materialize_device_survivors)

    from pegasus_tpu.runtime.tracing import COMPACT_TRACER

    best, out, split = float("inf"), None, {}
    for _ in range(reps + 1):
        t0 = time.perf_counter()
        if hasattr(backend, "survivors_device"):
            from pegasus_tpu.runtime.lane_guard import LANE_GUARD

            def _attempt():
                dev_idx, cnt = backend.survivors_device(packed_in, *fargs)
                t_merge = time.perf_counter()
                if dev_vals is not None:
                    # values come off the device; host gathers keys+aux
                    o = materialize_device_survivors(concat, dev_vals,
                                                     dev_idx, cnt)
                else:
                    # index download overlaps the memcpy-bound arena gather
                    o = gather_device_survivors(concat, dev_idx, cnt)
                return t_merge, o

            t1, out = LANE_GUARD.run(_attempt, None, op="bench-lane",
                                     deadline_s=_lane_deadline_s())
        else:
            surv = backend.survivors(packed_in, *fargs)
            t1 = time.perf_counter()
            with COMPACT_TRACER.span("gather", records=len(surv)):
                out = concat.gather(surv)
        total = time.perf_counter() - t0
        if total < best:
            best = total
            split = {"merge_s": round(t1 - t0, 3),
                     "gather_s": round(total - (t1 - t0), 3)}
    return best, out, split


def _clear_pipeline_caches():
    from pegasus_tpu.ops import compact as C

    C._compiled_pipeline.cache_clear()
    C._compiled_pipeline_cached.cache_clear()
    C._compiled_pipeline_cached_padded.cache_clear()


def _tpu_lanes(backend, prep, concat, fargs, reps):
    """Time BOTH device materialization strategies (host fused gather vs
    HBM-resident value rows) and return the best, with the loser's numbers
    kept in the split detail — the winner depends on the host's memcpy
    speed vs the tunnel's download bandwidth, which only a measurement on
    the actual box can settle. On real TPU hardware, additionally TRIAL
    the Pallas merge kernel self-validatingly (byte-equality against the
    XLA lane's output; any lowering failure is recorded, not fatal) —
    Pallas defaults off until a logged run proves it (VERDICT-r3 weak 4)."""
    import jax

    from pegasus_tpu.ops.compact import prepare_values

    tpu_s, out, split = _lane(backend, prep, concat, fargs, reps)
    split = dict(split, gather_path="host")
    best_dev_vals = None
    dev_vals = prepare_values(concat)  # flush-time upload: untimed
    if dev_vals is not None:
        s_b, out_b, split_b = _lane(backend, prep, concat, fargs, reps,
                                    dev_vals=dev_vals)
        if s_b < tpu_s:
            alt = {"path": "host", "tpu_compact_s": round(tpu_s, 3),
                   **{k: v for k, v in split.items() if k != "gather_path"}}
            tpu_s, out = s_b, out_b
            best_dev_vals = dev_vals
            split = dict(split_b, gather_path="device-values", alt=alt)
        else:
            split["alt"] = {"path": "device-values",
                            "tpu_compact_s": round(s_b, 3), **split_b}
    if (jax.default_backend() == "tpu"
            and os.environ.get("PEGASUS_PALLAS") is None):
        os.environ["PEGASUS_PALLAS"] = "1"
        _clear_pipeline_caches()
        try:
            s_p, out_p, split_p = _lane(backend, prep, concat, fargs, reps,
                                        dev_vals=best_dev_vals)
            if (out_p.n != out.n
                    or not np.array_equal(out_p.key_arena, out.key_arena)
                    or not np.array_equal(out_p.val_arena, out.val_arena)):
                split["pallas"] = {"status": "BYTE-MISMATCH vs xla lane",
                                   "tpu_compact_s": round(s_p, 3)}
            elif s_p < tpu_s:
                # keep the gather-strategy comparison from the xla pass:
                # the JSON line must still answer host-vs-device-values
                xla_alt = {"path": "xla", "tpu_compact_s": round(tpu_s, 3)}
                if "alt" in split:
                    xla_alt["alt"] = split["alt"]
                split = dict(split_p, gather_path=split["gather_path"],
                             kernel="pallas", alt=xla_alt)
                tpu_s, out = s_p, out_p
            else:
                split["pallas"] = {"status": "validated, slower",
                                   "tpu_compact_s": round(s_p, 3), **split_p}
        except Exception as e:  # noqa: BLE001 - lowering failure is data
            split["pallas"] = {"status": f"failed: {type(e).__name__}: "
                                         f"{str(e)[:200]}"}
        finally:
            os.environ.pop("PEGASUS_PALLAS", None)
            _clear_pipeline_caches()
    return tpu_s, out, split


def _compact_opts():
    from pegasus_tpu.ops.compact import CompactOptions

    opts = CompactOptions(backend="tpu", now=100, bottommost=True,
                          runs_sorted=True)
    return opts, (opts.now, opts.pidx, opts.partition_mask, True, True)


def tpu_lane_main():
    """Child process: backend init (doubles as the probe — one process,
    one lease) + full TPU lane. Prints ONE json line with timings and the
    output digest; the parent compares digests for byte equality.

    The device-health watchdog heartbeats to PEGASUS_BENCH_STATUS_FILE
    (set by the parent) for the whole lane: if the tunnel wedges and the
    parent has to abandon this child, the parent reads the heartbeat and
    reports WHICH stage wedged (device_init / pack / h2d / device /
    gather) instead of a bare timeout — the BENCH_r05 gap."""
    from pegasus_tpu.ops.device_watchdog import WATCHDOG
    from pegasus_tpu.runtime.tracing import COMPACT_TRACER

    WATCHDOG.status_path = os.environ.get("PEGASUS_BENCH_STATUS_FILE")
    # heartbeat-only until the platform is up: a probe-thread jit racing
    # our jax.config/platform init could bind the wrong backend, and a
    # probe starved behind a healthy-but-slow backend init would report a
    # false wedge. A wedge DURING init is still attributed — the heartbeat
    # keeps writing open_stages, and the parent's fallback reads the open
    # device_init span
    WATCHDOG.probes_armed = False
    WATCHDOG.start()

    n_total, n_runs, value_size, reps = _bench_params()
    t_init = time.perf_counter()
    with COMPACT_TRACER.span("device_init"):
        _enable_compile_cache()
        import jax

        platform = str(jax.devices()[0])
    init_s = time.perf_counter() - t_init
    WATCHDOG.probes_armed = True  # platform bound: liveness probes are safe
    print(f"tpu-lane: backend up in {init_s:.1f}s ({platform})",
          file=sys.stderr, flush=True)

    from pegasus_tpu.engine.block import KVBlock
    from pegasus_tpu.ops.compact import TpuBackend, pack_runs

    host_start = _host_info()
    runs, fill_s = _fill(n_total, n_runs, value_size)
    opts, fargs = _compact_opts()
    proc_t0 = time.process_time()
    with COMPACT_TRACER.session() as sess:
        packed = pack_runs(runs, opts, need_sbytes=False)
        concat = KVBlock.concat(runs)
        del runs
        backend = TpuBackend()
        prep = backend.prepare(packed)  # device residency: flush-time, untimed
        tpu_s, out, split = _tpu_lanes(backend, prep, concat, fargs, reps)
    from pegasus_tpu.runtime.lane_guard import LANE_GUARD

    result = {"ok": True, "tpu_s": tpu_s, "split": split,
              "platform": platform, "init_s": round(init_s, 1),
              "fill_s": round(fill_s, 3), "trace": sess.summary(),
              "process_s": round(time.process_time() - proc_t0, 3),
              "host": {"start": host_start, "end": _host_info()},
              # lane-guard totals: a run with fallbacks/abandons > 0 can
              # never silently masquerade as a clean tpu number
              "lane": LANE_GUARD.state()}
    result.update(_out_digest(out))
    print(json.dumps(result), flush=True)


def _run_tpu_lane_child(lane_timeout_s: float):
    """Spawn + babysit the TPU lane child. -> (result_dict | None, reason).

    Child stdout/stderr go to temp FILES: if the child wedges in backend
    init it gets abandoned, and an abandoned child holding an inherited
    pipe would block the driver's output capture after the parent exits.
    The child's watchdog heartbeats its stage/liveness state to a status
    FILE the parent reads on timeout — a wedged lane reports the stage it
    wedged at (stored in _LANE_STATE['wedge_status'] for the degraded
    detail) instead of only the generic message."""
    fake = os.environ.get("PEGASUS_BENCH_FAKE_LANE")
    status_f = tempfile.NamedTemporaryFile(prefix="bench_lane_",
                                           suffix=".status", delete=False)
    status_f.close()
    child_env = dict(os.environ, PEGASUS_BENCH_STATUS_FILE=status_f.name)
    if fake == "sleep":  # test hook: simulates a post-probe tunnel wedge
        cmd = [sys.executable, "-c", "import time; time.sleep(3600)"]
    elif fake == "wedge":  # test hook: a wedge AFTER the watchdog captured
        # the stage — exercises the parent's status-file read path
        cmd = [sys.executable, "-c",
               "import json, os, time; json.dump("
               "{'wedged_at_stage': 'device', 'last_ok': time.time()},"
               " open(os.environ['PEGASUS_BENCH_STATUS_FILE'], 'w'));"
               " time.sleep(3600)"]
    elif fake == "crash":  # test hook: simulates backend-init death
        cmd = [sys.executable, "-c",
               "import sys; print('boom', file=sys.stderr); sys.exit(7)"]
    else:
        cmd = [sys.executable, os.path.abspath(__file__), "--tpu-lane"]
    out_f = tempfile.NamedTemporaryFile(prefix="bench_lane_", suffix=".out",
                                        delete=False)
    err_f = tempfile.NamedTemporaryFile(prefix="bench_lane_", suffix=".err",
                                        delete=False)
    with out_f, err_f:
        proc = subprocess.Popen(
            cmd, stdout=out_f, stderr=err_f, stdin=subprocess.DEVNULL,
            cwd=os.path.dirname(os.path.abspath(__file__)), env=child_env)
        _LANE_STATE["proc"] = proc
        _LANE_STATE["files"] = [out_f.name, err_f.name, status_f.name]
        abandoned = timed_out = False
        try:
            proc.wait(timeout=lane_timeout_s)
        except subprocess.TimeoutExpired:
            # SIGTERM + grace, then ABANDON — never SIGKILL a TPU-attached
            # process (it wedges the tunnel's device lease for hours)
            timed_out = True
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                abandoned = True
    with open(err_f.name, "r", errors="replace") as f:
        err_tail = " | ".join(f.read().strip().splitlines()[-3:])[-400:]
    with open(out_f.name, "r", errors="replace") as f:
        stdout = f.read()
    status = None
    try:
        with open(status_f.name, "r") as f:
            status = json.loads(f.read() or "null")
    except (OSError, ValueError):
        pass
    _LANE_STATE["wedge_status"] = status
    for name in (out_f.name, err_f.name, status_f.name):
        try:
            os.unlink(name)
        except OSError:
            pass
    result = None
    for line in stdout.strip().splitlines():
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except ValueError:
                pass
    if result is not None and result.get("ok"):
        return result, ""
    if timed_out:
        how = ("ignored SIGTERM; child abandoned"
               if abandoned or proc.returncode is None else "terminated")
        where = ""
        if status and status.get("wedged_at_stage"):
            where = f"; wedged at stage: {status['wedged_at_stage']}"
        elif status and status.get("open_stages"):
            open_all = [s for st in status["open_stages"].values() for s in st]
            if open_all:
                where = f"; last open stage: {open_all[-1]}"
        return None, (f"tpu lane exceeded {lane_timeout_s:.0f}s (device "
                      f"tunnel wedged mid-init or mid-run){where}; {how}")
    if proc.returncode != 0:
        return None, (f"tpu lane died rc={proc.returncode}: {err_tail}")
    return None, "tpu lane exited 0 but produced no result line: " + err_tail


def _arm_watchdog():
    """Absolute backstop: the parent itself must never outlive the driver
    budget even if some host-side step stalls. Hard-exit with a parseable
    degraded line after PEGASUS_BENCH_TIMEOUT_S (0 disables)."""
    import threading

    budget = int(os.environ.get("PEGASUS_BENCH_TIMEOUT_S", 600))
    if budget <= 0:
        return

    def boom():
        print(f"bench watchdog: no result after {budget}s — emitting the "
              f"degraded line and exiting. Last recorded measurements are "
              f"in BASELINE.md.", file=sys.stderr, flush=True)
        # emit FIRST: signalling the child wakes the main thread out of
        # proc.wait(), and any file cleanup here would race it into a
        # crash path that could print a second JSON line. The two temp
        # files leak at hard-exit — harmless vs a corrupted artifact.
        if not _RESULT_PRINTED:
            if os.environ.get("PEGASUS_BENCH_MODE") == "ycsb":
                _emit(_ycsb_degraded(f"watchdog fired after {budget}s"))
            elif os.environ.get("PEGASUS_BENCH_MODE") == "learn":
                _emit(_learn_degraded(f"watchdog fired after {budget}s"))
            elif os.environ.get("PEGASUS_BENCH_MODE") == "native":
                _emit(_native_degraded(f"watchdog fired after {budget}s"))
            else:
                n_total, n_runs, value_size, _ = _bench_params()
                _emit(_degraded(n_total, n_runs, value_size,
                                f"watchdog fired after {budget}s",
                                detail=_CPU_DETAIL))
        proc = _LANE_STATE["proc"]
        if proc is not None and proc.poll() is None:
            proc.send_signal(signal.SIGTERM)  # SIGTERM only, never SIGKILL
        # rc 0: the driver's artifact is (rc, parsed line); a degraded
        # line that parses is a working bench reporting a broken tunnel
        os._exit(0)

    t = threading.Timer(budget, boom)
    t.daemon = True
    t.start()


def _ycsb_params():
    """(records, ops, threads, partitions, value_size) for the serving
    bench — single source for the lane, the watchdog, and the crash
    handler so a degraded line's metric name matches the success path's."""
    return (int(os.environ.get("PEGASUS_BENCH_YCSB_RECORDS", 10_000)),
            int(os.environ.get("PEGASUS_BENCH_YCSB_OPS", 20_000)),
            int(os.environ.get("PEGASUS_BENCH_YCSB_THREADS", 8)),
            int(os.environ.get("PEGASUS_BENCH_YCSB_PARTITIONS", 32)),
            int(os.environ.get("PEGASUS_BENCH_VALUE", 100)))


def _ycsb_mix():
    """(mix letter, read fraction): PEGASUS_BENCH_YCSB_MIX selects the
    YCSB op mix — 'a' 50/50 read/update (default), 'b' 95/5,
    'c' 100/0 read-only, 'e' 95/5 short-scan/insert (the YCSB-E shape:
    the "read" is a bounded multi_get range under one hashkey). The
    read-heavy variants are the device-served read A/B workload, and 'e'
    the device-served RANGE-read one (run with PEGASUS_DEVICE_READS=1 vs
    0 against a tpu-backend onebox on hardware; see ROADMAP)."""
    m = (os.environ.get("PEGASUS_BENCH_YCSB_MIX", "a").strip().lower()
         or "a")
    return m, {"a": 0.5, "b": 0.95, "c": 1.0, "e": 0.95}.get(m, 0.5)


def _ycsb_metric_name() -> str:
    records, ops, threads, partitions, value_size = _ycsb_params()
    mix, read_frac = _ycsb_mix()
    pct = int(round(read_frac * 100))
    shape = "scan-insert" if mix == "e" else "read-update"
    return (f"YCSB-{mix.upper()} {pct}/{100 - pct} {shape} ops/sec "
            f"({records} records, "
            f"{ops} ops, {threads} threads, {partitions} partitions, "
            f"value={value_size}B)")


def _ycsb_degraded(reason: str, detail: dict = None) -> dict:
    d = {"degraded": True, "reason": reason}
    d.update(detail or {})
    return {"metric": _ycsb_metric_name(), "value": None, "unit": "ops/s",
            "vs_baseline": None, "detail": d}


class ZipfKeys:
    """YCSB's quick-zipfian rank generator (Gray et al., SIGMOD '94
    "Quickly generating billion-record synthetic databases"): ranks over
    [0, n) with P(rank k) ~ 1/(k+1)^theta. The naive continuous inverse
    transform (`u ** (1/(1-theta))`) is NOT zipf — at theta=0.99 it puts
    ~91% of all picks on rank 0, so an ops/sec number produced with it
    measures one hot key on one partition instead of a skewed workload."""

    def __init__(self, n: int, theta: float = 0.99):
        self.n = n
        self.zetan = float(np.sum(1.0 / np.arange(1, n + 1) ** theta))
        self.zeta2 = 1.0 + 0.5 ** theta
        self.alpha = 1.0 / (1.0 - theta)
        self.eta = ((1.0 - (2.0 / n) ** (1.0 - theta))
                    / (1.0 - self.zeta2 / self.zetan))

    def pick(self, rng) -> int:
        u = rng.random()
        uz = u * self.zetan
        if uz < 1.0:
            return 0
        if uz < self.zeta2:
            return 1
        return min(self.n - 1,
                   int(self.n * (self.eta * u - self.eta + 1.0) ** self.alpha))


def _max_quantiles(dicts):
    """Collector-style merge of percentile dicts across partitions: the
    max per quantile (the worst partition bounds the fleet)."""
    out = {}
    for d in dicts:
        for q, v in d.items():
            out[q] = max(out.get(q, 0), v)
    return out


_YCSB_E_GROUP = 100  # sortkeys per hashkey in the mix='e' load shape


def _ycsb_load_and_run(box, records, n_ops, n_threads, value,
                       read_frac: float = 0.5, during=None,
                       tables=("ycsb",), scan_mix: bool = False):
    """Shared YCSB workload driver: load `records`, run the read/update
    mix (`read_frac` reads) from `n_threads` clients. -> stats dict (the
    sweep mode reruns this once per group count). `during`, when given,
    runs on its own thread WHILE the workers hammer the cluster (the
    consistency audit rides here: digests must match under concurrent
    load, not just at rest); its return value lands in stats["during"].
    With multiple `tables` the record budget splits evenly and each
    worker thread pins one table (tid % len(tables)) — the multi-tenant
    shape the per-table ledger breakdown attributes.

    scan_mix=True is the YCSB-E shape: records load as _YCSB_E_GROUP
    sortkeys per hashkey, the read op is a SHORT SCAN (bounded multi_get
    range from a random start sortkey, length uniform 1.._YCSB_E_GROUP —
    the device range-read path) and the write op an INSERT of a fresh
    row, latencies in bench.ycsb.{scan,insert}_latency_us."""
    from pegasus_tpu.client import MetaResolver, PegasusClient
    from pegasus_tpu.runtime.perf_counters import counters
    from pegasus_tpu.runtime.tasking import spawn_thread

    tables = tuple(tables) or ("ycsb",)
    per_records = records if len(tables) == 1 else max(1,
                                                      records // len(tables))

    def load_key(i):
        if scan_mix:
            return (b"user%09d" % (i // _YCSB_E_GROUP),
                    b"s%04d" % (i % _YCSB_E_GROUP))
        return b"user%012d" % i, b"f0"

    t0 = time.perf_counter()
    for table in tables:
        load_cli = PegasusClient(MetaResolver([box.meta_addr], table))
        for i in range(per_records):
            hk, sk = load_key(i)
            load_cli.set(hk, sk, value)
        load_cli.close()
    load_s = time.perf_counter() - t0

    errors = [0]
    read_lat = counters.percentile("bench.ycsb.read_latency_us")
    update_lat = counters.percentile("bench.ycsb.update_latency_us")
    scan_lat = counters.percentile("bench.ycsb.scan_latency_us")
    insert_lat = counters.percentile("bench.ycsb.insert_latency_us")
    zipf = ZipfKeys(per_records)

    def worker(tid):
        import random

        rng = random.Random(tid)
        cli = PegasusClient(MetaResolver([box.meta_addr],
                                         tables[tid % len(tables)]))
        inserts = 0
        for _ in range(n_ops // n_threads):
            pick = zipf.pick(rng)
            s = time.perf_counter()
            try:
                if scan_mix:
                    if rng.random() < read_frac:
                        hk = b"user%09d" % (pick // _YCSB_E_GROUP)
                        first = rng.randrange(_YCSB_E_GROUP)
                        cli.multi_get(
                            hk, None,
                            max_kv_count=rng.randint(1, _YCSB_E_GROUP),
                            start_sortkey=b"s%04d" % first)
                        scan_lat.set(int((time.perf_counter() - s) * 1e6))
                    else:
                        # fresh rows keyed per thread: inserts, not updates
                        cli.set(b"insert%03d" % tid, b"s%08d" % inserts,
                                value)
                        inserts += 1
                        insert_lat.set(int((time.perf_counter() - s) * 1e6))
                    continue
                k = b"user%012d" % pick
                if rng.random() < read_frac:
                    cli.get(k, b"f0")
                    read_lat.set(int((time.perf_counter() - s) * 1e6))
                else:
                    cli.set(k, b"f0", value)
                    update_lat.set(int((time.perf_counter() - s) * 1e6))
            except Exception:
                errors[0] += 1
        cli.close()

    threads = [spawn_thread(worker, t, daemon=False, start=False)
               for t in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    during_box = [None]
    during_thread = None
    if during is not None:
        def _run_during():
            try:
                during_box[0] = during()
            except Exception as e:  # noqa: BLE001 - report, don't crash
                during_box[0] = {"error": repr(e)}
        during_thread = spawn_thread(_run_during, daemon=False)
    for t in threads:
        t.join()
    run_s = time.perf_counter() - t0
    if during_thread is not None:
        during_thread.join()
    done_ops = n_threads * (n_ops // n_threads)
    return {
        "during": during_box[0],
        "ops_s": round(done_ops / run_s, 1),
        "run_s": round(run_s, 2),
        "load_s": round(load_s, 2),
        "load_ops_s": round(per_records * len(tables) / max(load_s, 1e-9), 1),
        "errors": errors[0],
        "client_latency_us": (
            {"scan": scan_lat.percentiles(),
             "insert": insert_lat.percentiles()} if scan_mix else
            {"read": read_lat.percentiles(),
             "update": update_lat.percentiles()}),
    }


def _ycsb_table_breakdown(meta_addr):
    """Per-table capacity attribution for the run (ISSUE 18): fold every
    node's `table-stats` ledger fragments into cluster-wide per-table
    series + the top-k ranking — the same merge the collector performs,
    driven here through the public remote-command surface so the bench
    exercises the wire path, not process-local state."""
    from pegasus_tpu.collector.cluster_doctor import ClusterCaller
    from pegasus_tpu.runtime.table_stats import fold_snapshots, top_k

    caller = ClusterCaller([meta_addr])
    try:
        state = caller.meta_state() or {}
        frags = []
        for addr, node in sorted((state.get("nodes") or {}).items()):
            if not node.get("alive", False):
                continue
            try:
                reply = json.loads(caller.remote_command(
                    addr, "table-stats", []))
            except Exception:  # noqa: BLE001 - attribution is best-effort
                continue
            if isinstance(reply, dict):
                frags.extend(v for v in reply.values() if isinstance(v, dict))
        folded = fold_snapshots(frags)
        return {"tables": folded, "top": top_k(folded)}
    finally:
        caller.close()


def _ycsb_group_sweep(groups_list):
    """PEGASUS_BENCH_YCSB_GROUPS=1,4: the partition-group scaling
    artifact. The SAME YCSB-A workload runs once per group count, each
    against a fresh onebox whose replica nodes serve through that many
    shared-nothing group-executor processes (groups=1 is the one-GIL
    ceiling, through the identical router architecture, so the sweep
    isolates the sharding win). Emits ONE json line whose value is the
    best ops/s and whose detail.sweep records every run + the host's
    contention state (per-group worker processes show up in loadavg)."""
    records, n_ops, n_threads, partitions, value_size = _ycsb_params()
    from tools._onebox import Onebox

    from pegasus_tpu.runtime.perf_counters import counters

    value = os.urandom(value_size)
    sweep = []
    for g in groups_list:
        # fresh latency windows per sweep entry: the percentile counters
        # are process-global and would otherwise blend the runs
        counters.remove("bench.ycsb.read_latency_us")
        counters.remove("bench.ycsb.update_latency_us")
        counters.remove("bench.ycsb.scan_latency_us")
        counters.remove("bench.ycsb.insert_latency_us")
        host_start = _host_info()
        box = Onebox("ycsb", partitions=partitions, serve_groups=g)
        try:
            stats = _ycsb_load_and_run(box, records, n_ops, n_threads, value,
                                       read_frac=_ycsb_mix()[1],
                                       scan_mix=_ycsb_mix()[0] == "e")
        finally:
            box.stop()
        entry = {"groups": g, "host": {"start": host_start,
                                       "end": _host_info()}}
        entry.update(stats)
        sweep.append(entry)
        print(f"ycsb sweep: groups={g} -> {stats['ops_s']} ops/s "
              f"(errors={stats['errors']})", file=sys.stderr, flush=True)
    base = next((e for e in sweep if e["groups"] == 1), None)
    best = max(sweep, key=lambda e: e["ops_s"])
    detail = {
        "sweep": sweep,
        "partitions": partitions, "threads": n_threads, "records": records,
        "scaling_vs_groups1": (round(best["ops_s"] / base["ops_s"], 3)
                               if base and base["ops_s"] else None),
    }
    _emit({
        "metric": (f"YCSB-{_ycsb_mix()[0].upper()} ops/sec, "
                   f"serve-group sweep groups="
                   f"{','.join(str(g) for g in groups_list)} "
                   f"({records} records, {n_ops} ops, {n_threads} threads, "
                   f"{partitions} partitions, value={value_size}B)"),
        "value": best["ops_s"],
        "unit": "ops/s",
        "vs_baseline": detail["scaling_vs_groups1"],
        "detail": detail,
    })


def ycsb_main():
    """PEGASUS_BENCH_MODE=ycsb: the serving-path lane — BASELINE.json's
    SECOND metric (YCSB-A 50/50 read/update over hash partitions), never
    recorded before this lane existed. Boots an in-process onebox (1 meta
    + 3 replica nodes over real sockets), loads N records, drives 50/50
    read/update from T client threads, and prints ONE json line with
    ops/sec, per-op-class p99 (from the server's <op>_latency_us
    percentiles), the plog group-size histogram and
    replica.prepare_latency_us (so the group-commit win is attributable),
    and a detail.host block (so host contention can't masquerade as a
    regression).

    PEGASUS_BENCH_YCSB_GROUPS=1,4 switches to the partition-group SWEEP:
    the same workload repeated per group count with the replica nodes
    split into that many shared-nothing executor processes
    (replication/serve_groups.py) — the scaling artifact for the
    serve-group work (BENCH_r06-ready).

    The serving path is host-only: jax is pinned to the cpu platform
    BEFORE any engine import, so this mode never touches the axon device
    lease the compaction bench's child-process discipline protects."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    _enable_compile_cache()

    groups_env = os.environ.get("PEGASUS_BENCH_YCSB_GROUPS", "").strip()
    if groups_env:
        groups_list = [max(1, int(x)) for x in groups_env.split(",") if x]
        _ycsb_group_sweep(groups_list)
        return

    records, n_ops, n_threads, partitions, value_size = _ycsb_params()
    from pegasus_tpu.runtime.perf_counters import counters

    from tools._onebox import Onebox

    host_start = _host_info()
    proc_t0 = time.process_time()
    mix, read_frac = _ycsb_mix()
    n_tables = max(1, int(os.environ.get("PEGASUS_BENCH_YCSB_TABLES", "1")))
    ycsb_tables = ["ycsb"] + [f"ycsb{i}" for i in range(2, n_tables + 1)]
    box = Onebox("ycsb", partitions=partitions)
    try:
        for extra in ycsb_tables[1:]:
            box.cluster.create(extra, partitions=partitions).close()
        value = os.urandom(value_size)

        def audit_under_load():
            """Decree-anchored consistency audit WHILE the workload runs
            (ISSUE 8 acceptance): every replica must digest identical
            state at identical decrees under concurrent YCSB traffic. A
            mismatch fails the whole bench run — a throughput number from
            a cluster serving divergent replicas is worthless."""
            from pegasus_tpu.collector.cluster_doctor import \
                run_cluster_audit

            return run_cluster_audit([box.meta_addr], apps=ycsb_tables,
                                     wait_s=20.0)

        stats = _ycsb_load_and_run(box, records, n_ops, n_threads, value,
                                   read_frac=read_frac,
                                   during=audit_under_load,
                                   tables=ycsb_tables,
                                   scan_mix=mix == "e")
        audit = stats.pop("during") or {}
        audit.pop("digests", None)  # per-node digests: bulky, summarized
        # zero mismatches is only a PASS when the audit actually compared
        # every partition — an errored or inconclusive audit must not
        # pose as validation (the mismatch gate below stays the only
        # run-failing condition, per the acceptance criterion)
        audit["conclusive"] = (not audit.get("error")
                               and audit.get("partitions", 0) > 0
                               and len(audit.get("ok", []))
                               == audit.get("partitions"))
        if not audit["conclusive"]:
            print(f"ycsb: consistency audit INCONCLUSIVE — zero "
                  f"mismatches is vacuous here: {audit}",
                  file=sys.stderr, flush=True)
        if audit.get("mismatches"):
            # flight recorder (ISSUE 12): capture the cluster's recorded
            # past NOW, while the onebox still serves — the degraded
            # line below references the artifact instead of asking for a
            # re-reproduction
            try:
                from pegasus_tpu.collector.flight_recorder import RECORDER

                inc = RECORDER.capture(
                    [box.meta_addr],
                    reason=f"ycsb audit mismatch x{len(audit['mismatches'])}",
                    trigger="bench")
                audit["incident"] = {"id": inc["id"], "path": inc["path"]}
            except Exception as e:  # capture must not mask the mismatch
                print(f"ycsb: incident capture failed: {e!r}",
                      file=sys.stderr, flush=True)

        # ---- attribution: server-side latency percentiles per op class
        # (max across partitions, the collector's merge rule), the plog
        # group-commit histogram, and the prepare round's latency
        snap = counters.snapshot()
        server_lat = {}
        for op in ("get", "put"):
            dicts = [v for k, v in snap.items()
                     if k.startswith("app.") and k.endswith(f".{op}_latency_us")
                     and isinstance(v, dict)]
            if dicts:
                server_lat[op] = _max_quantiles(dicts)
        append_count = flush_count = 0
        for stub in box.cluster.stubs:
            for rep in stub._replicas.values():
                append_count += rep.plog.append_count
                flush_count += rep.plog.flush_count

        # ---- device-served reads attribution (ISSUE 7): per-stage read
        # spans, device probe totals and the read lane guard's state. The
        # same fallback-free rule the compaction bench applies: a run
        # whose read lane degraded (fallbacks/abandons > 0) must never
        # pass its device-read throughput off as a clean device number.
        from pegasus_tpu.runtime.lane_guard import READ_LANE_GUARD

        read_lane = READ_LANE_GUARD.state()
        reads_detail = {
            "mix": mix,
            "read_fraction": read_frac,
            "device": {
                "lookup_count": snap.get("read.device.lookup_count", 0),
                "keys": snap.get("read.device.keys", 0),
                "hits": snap.get("read.device.hits", 0),
            },
            "batch_size": snap.get("read.batch.size"),
            "spans": {k: v for k, v in snap.items()
                      if k.startswith("compact.stage.read.")},
            "lane": read_lane,
            "device_numbers_degraded": bool(
                read_lane["fallbacks"] or read_lane["deadline_abandons"]),
            # device-served RANGE reads (ISSUE 19): the scan path's own
            # totals + span durations and the same fallback-free rule —
            # a degraded lane's scan throughput is not a device number
            "scan": {
                "range": {k: snap.get("read.range." + k, 0)
                          for k in ("batch_count", "rows", "device_count",
                                    "host_count", "reverse_host_count")},
                "batch_size": snap.get("read.range.batch.size"),
                "spans": {k: v for k, v in snap.items()
                          if k.startswith("compact.stage.read.range")},
                "device_numbers_degraded": bool(
                    read_lane["fallbacks"]
                    or read_lane["deadline_abandons"]),
            },
        }
        result = {
            "metric": _ycsb_metric_name(),
            "value": stats["ops_s"],
            "unit": "ops/s",
            "vs_baseline": None,  # first recording of this BASELINE metric
            "detail": {
                "run_s": stats["run_s"],
                "load_s": stats["load_s"],
                "load_ops_s": stats["load_ops_s"],
                "errors": stats["errors"],
                "client_latency_us": stats["client_latency_us"],
                "server_latency_us": server_lat,
                "prepare_latency_us": snap.get("replica.prepare_latency_us"),
                "plog": {
                    "group_size": snap.get("plog.append.group_size"),
                    "append_count": append_count,
                    "flush_count": flush_count,
                    "group_ratio": round(
                        append_count / max(flush_count, 1), 3),
                },
                "partitions": partitions,
                "threads": n_threads,
                "records": records,
                "reads": reads_detail,
                # debt-driven admission control (ISSUE 10): whether the
                # graduated backpressure engaged during the run — a
                # nonzero delay count with zero rejects is the designed
                # "measured slowdown instead of a stall" shape
                "throttle": {
                    "debt_delay_count": snap.get(
                        "engine.throttle.debt_delay_count", 0),
                    "debt_reject_count": snap.get(
                        "engine.throttle.debt_reject_count", 0),
                    "debt_delay_ms": snap.get(
                        "engine.throttle.debt_delay_ms"),
                    "sched_deferred_count": snap.get(
                        "engine.compact.sched.deferred_count", 0),
                    "sched_urgent_count": snap.get(
                        "engine.compact.sched.urgent_count", 0),
                },
                "audit": audit,
                "cpu_process_s": round(time.process_time() - proc_t0, 3),
                "host": {"start": host_start, "end": _host_info()},
            },
        }
        if n_tables > 1:
            # multi-tenant breakdown (ISSUE 18): which table consumed the
            # run's capacity, folded from the nodes' per-table ledgers
            result["detail"]["tables"] = _ycsb_table_breakdown(box.meta_addr)
    finally:
        box.stop()
    if audit.get("mismatches"):
        # a digest mismatch under load is a CORRECTNESS failure: the
        # throughput number must not stand
        _emit(_ycsb_degraded(
            f"consistency audit FAILED: {len(audit['mismatches'])} digest "
            f"mismatch(es) — {audit['mismatches']}",
            detail=result["detail"]))
        return
    _emit(result)


# ----------------------------------------------------------- native A/B

# the native read data plane's attribution series (ISSUE 20): totals are
# deltas across each run so the A/B legs are cleanly separable
_NATIVE_COUNTERS = ("native.wave_count", "native.batch_frames",
                    "native.writev_count", "native.writev_bytes",
                    "native.sst_mmap_count")


def _native_metric_name() -> str:
    records, n_ops, n_threads, partitions, value_size = _ycsb_params()
    return (f"YCSB-C read-only ops/sec with PEGASUS_NATIVE=1 "
            f"(A/B vs =0 over mixes b/c/e + pipelined batch_get; "
            f"{records} records, {n_ops} ops, "
            f"{n_threads} threads, {partitions} partitions, "
            f"value={value_size}B)")


def _native_degraded(reason: str, detail: dict = None) -> dict:
    d = {"degraded": True, "reason": reason}
    d.update(detail or {})
    return {"metric": _native_metric_name(), "value": None, "unit": "ops/s",
            "vs_baseline": None, "detail": d}


def _native_pipelined_leg(box, records, n_ops, n_threads, value):
    """Pipelined point-read leg for the native A/B. The YCSB mixes issue
    one blocking call per thread at a time, so no multi-frame wave ever
    reaches a connection and the binned-dispatch / vectored-reply stages
    sit idle (their counters flatline in both legs). This leg drives
    `PegasusClient.batch_get` — 32 keys per wave per thread — which is
    exactly the shape the C plane amortizes: the client send is one
    vectored sendmsg, the server bins the hot RPC_GET wave into one
    `on_get_batch`, and the replies leave as one vectored write.
    Self-checking: every read verifies the loaded value."""
    import random

    from pegasus_tpu.client import MetaResolver, PegasusClient
    from pegasus_tpu.runtime.tasking import spawn_thread

    wave_keys = 32
    load_cli = PegasusClient(MetaResolver([box.meta_addr], "ycsb"))
    for i in range(records):
        load_cli.set(b"user%012d" % i, b"f0", value)
    load_cli.close()

    done = [0] * n_threads
    errors = [0] * n_threads

    def worker(tid):
        rng = random.Random(0xBA7C4 + tid)
        cli = PegasusClient(MetaResolver([box.meta_addr], "ycsb"))
        try:
            per = n_ops // n_threads
            while done[tid] < per:
                items = [(b"user%012d" % rng.randrange(records), b"f0")
                         for _ in range(min(wave_keys, per - done[tid]))]
                vals = cli.batch_get(items)
                errors[tid] += sum(1 for v in vals if v != value)
                done[tid] += len(items)
        finally:
            cli.close()

    t0 = time.perf_counter()
    threads = [spawn_thread(worker, tid, daemon=False, start=False)
               for tid in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    run_s = time.perf_counter() - t0
    ops = sum(done)
    return {"ops_s": round(ops / max(run_s, 1e-9), 1),
            "run_s": round(run_s, 2), "errors": sum(errors)}


def native_main():
    """PEGASUS_BENCH_MODE=native: the native-read-data-plane A/B
    (ISSUE 20, BENCH_native artifact). The SAME YCSB workload runs with
    PEGASUS_NATIVE=0 (pure-Python frame loop, per-frame sendall, copying
    SST reads) then =1 (C binned dispatch waves, vectored sendmsg
    replies, zero-copy mmap SST sections) for each of the read-heavy
    mixes b (95/5), c (read-only) and e (short-scan), plus a PIPELINED
    batch_get leg that actually forms multi-frame waves (the blocking
    YCSB threads never do) — fresh onebox per leg, both legs
    byte-identical on the wire (test-enforced). Each side scores its
    best of PEGASUS_BENCH_NATIVE_REPS interleaved reps (a discarded
    warmup leg eats the jit compiles first). Emits ONE
    json line: value = mix c's native-on ops/s, vs_baseline = mix c's
    on/off ratio, detail.mixes the full grid with per-stage native.*
    counter deltas attributing where the native plane actually ran.
    Host-only (JAX_PLATFORMS=cpu): no TPU lease needed."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    _enable_compile_cache()
    records, n_ops, n_threads, partitions, value_size = _ycsb_params()
    from pegasus_tpu.runtime.perf_counters import counters

    from tools._onebox import Onebox

    host_start = _host_info()
    value = os.urandom(value_size)
    prior = os.environ.get("PEGASUS_NATIVE")
    reps = int(os.environ.get("PEGASUS_BENCH_NATIVE_REPS", 3))
    mixes = {}

    def run_leg(mix, nat):
        os.environ["PEGASUS_NATIVE"] = nat
        # fresh latency windows per leg: the percentile counters
        # are process-global and would otherwise blend the runs
        counters.remove("bench.ycsb.read_latency_us")
        counters.remove("bench.ycsb.update_latency_us")
        counters.remove("bench.ycsb.scan_latency_us")
        counters.remove("bench.ycsb.insert_latency_us")
        base = {name: counters.rate(name).total()
                for name in _NATIVE_COUNTERS}
        box = Onebox("ycsb", partitions=partitions)
        try:
            if mix == "pipelined":
                stats = _native_pipelined_leg(
                    box, records, n_ops, n_threads, value)
            else:
                read_frac = {"b": 0.95, "c": 1.0, "e": 0.95}[mix]
                stats = _ycsb_load_and_run(
                    box, records, n_ops, n_threads, value,
                    read_frac=read_frac, scan_mix=mix == "e")
        finally:
            box.stop()
        leg = {
            "ops_s": stats["ops_s"],
            "run_s": stats["run_s"],
            "errors": stats["errors"],
            "native_counters": {
                name: counters.rate(name).total() - base[name]
                for name in _NATIVE_COUNTERS},
        }
        if "client_latency_us" in stats:
            leg["client_latency_us"] = stats["client_latency_us"]
        print(f"native A/B: mix={mix} PEGASUS_NATIVE={nat} -> "
              f"{stats['ops_s']} ops/s (errors={stats['errors']})",
              file=sys.stderr, flush=True)
        return leg

    try:
        # discarded warmup leg: the first onebox in a process eats the
        # jit compiles and thread-pool spin-up; neither side should
        run_leg("c", "0")
        for mix in ("b", "c", "e", "pipelined"):
            # identical legs vary ±25% on a loaded 1-cpu host, so a
            # single-shot A/B is noise: interleave off/on reps (drift
            # hits both sides alike) and score each side by its best
            # rep — the run least disturbed by the host
            legs = {"0": [], "1": []}
            for _ in range(reps):
                for nat in ("0", "1"):
                    legs[nat].append(run_leg(mix, nat))
            entry = {}
            for nat in ("0", "1"):
                best = max(legs[nat], key=lambda leg: leg["ops_s"])
                best["rep_ops_s"] = [leg["ops_s"] for leg in legs[nat]]
                entry["on" if nat == "1" else "off"] = best
            entry["ratio"] = round(
                entry["on"]["ops_s"] / max(entry["off"]["ops_s"], 1e-9), 3)
            mixes[mix] = entry
    finally:
        if prior is None:
            os.environ.pop("PEGASUS_NATIVE", None)
        else:
            os.environ["PEGASUS_NATIVE"] = prior
    _emit({
        "metric": _native_metric_name(),
        "value": mixes["c"]["on"]["ops_s"],
        "unit": "ops/s",
        "vs_baseline": mixes["c"]["ratio"],
        "detail": {
            "mixes": mixes,
            "records": records, "ops": n_ops, "threads": n_threads,
            "partitions": partitions, "value_size": value_size,
            "host": {"start": host_start, "end": _host_info()},
        },
    })


def _learn_params():
    """(records, value_size) for PEGASUS_BENCH_MODE=learn — single
    source for the lane, the watchdog and the crash handler so a
    degraded line's metric name matches the success path's."""
    return (int(os.environ.get("PEGASUS_BENCH_LEARN_RECORDS", 20_000)),
            int(os.environ.get("PEGASUS_BENCH_VALUE", 100)))


def _learn_metric_name() -> str:
    records, value_size = _learn_params()
    return (f"learn ship: monolithic vs streamed-delta bytes ratio "
            f"({records} records, value={value_size}B)")


def _learn_degraded(reason: str, detail: dict = None) -> dict:
    d = {"degraded": True, "reason": reason}
    d.update(detail or {})
    return {"metric": _learn_metric_name(), "value": None, "unit": "x",
            "vs_baseline": None, "detail": d}


def learn_main():
    """PEGASUS_BENCH_MODE=learn: the block-shipped learning artifact
    (ISSUE 13) — wall clock + shipped bytes for the three ways a replica
    can be (re-)seeded at N records, all in-process on CPU:

      * monolithic: the legacy whole-state copy (every checkpoint file
        read into memory and shipped, learner rebuilt from scratch);
      * full ship:  the streaming block plane, learner starting empty
        (same bytes as monolithic, but chunked/resumable/pinned);
      * delta ship: the streaming plane re-learning a learner that
        already holds the SSTs (the balancer-move/restart case the delta
        handshake exists for) after a small write burst on the primary;
      * replay:     log-replay-only catch-up of the same history — the
        baseline the ship path replaces for bulk state.

    Every learn's engine digest is compared against the primary at equal
    committed decrees (a transfer that loses bytes must fail the bench,
    not report a speed). One JSON line; degraded-line semantics match
    the YCSB mode."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    _enable_compile_cache()
    import shutil
    import tempfile

    from pegasus_tpu.base.utils import epoch_now
    from pegasus_tpu.engine import EngineOptions
    from pegasus_tpu.engine.server_impl import RPC_MULTI_PUT
    from pegasus_tpu.replication.replica import GroupView, Replica
    from pegasus_tpu.rpc import messages as rpc_msg
    from pegasus_tpu.runtime.perf_counters import counters

    records, value_size = _learn_params()
    host_start = _host_info()
    tmp = tempfile.mkdtemp(prefix="pegasus_learn_bench_")
    # small memtables so the loaded state lands in SSTs (the thing the
    # block plane ships); cpu backend end to end — no TPU lease needed
    # to measure the replay-vs-ship win
    opts = lambda: EngineOptions(backend="cpu", memtable_bytes=256 << 10)  # noqa: E731
    reps = []

    def open_replica(name):
        r = Replica(name, os.path.join(tmp, name), options=opts(), quorum=1)
        reps.append(r)
        return r

    def ship_totals():
        return {k: counters.rate(f"learn.ship.{k}").total()
                for k in ("blocks", "bytes", "delta_skipped_blocks")}

    try:
        prim = open_replica("prim")
        prim.assume_view(GroupView(1, "prim", []))
        value = os.urandom(value_size)
        t0 = time.perf_counter()
        per = 100
        for base in range(0, records, per):
            kvs = [rpc_msg.KeyValue(b"s%08d" % i, value)
                   for i in range(base, min(base + per, records))]
            prim.client_write(RPC_MULTI_PUT, rpc_msg.MultiPutRequest(
                hash_key=b"h%05d" % (base % 97), kvs=kvs))
        load_s = time.perf_counter() - t0
        prim.server.engine.flush()
        now = epoch_now()

        def run_learn(learner, peer):
            before, t0 = ship_totals(), time.perf_counter()
            learner.learn_from(peer)
            after = ship_totals()
            ld = learner.server.engine.state_digest(now=now)
            pd = prim.server.engine.state_digest(now=now)
            return {
                "wall_s": round(time.perf_counter() - t0, 3),
                "bytes": after["bytes"] - before["bytes"],
                "blocks": after["blocks"] - before["blocks"],
                "delta_skipped_blocks": (after["delta_skipped_blocks"]
                                         - before["delta_skipped_blocks"]),
                "digest_match": (ld["digest"] == pd["digest"]
                                 and learner.last_committed
                                 == prim.last_committed),
            }

        class _MonolithicPeer:
            """Peer exposing ONLY the legacy surface, so learn_from
            takes the monolithic path against the same primary."""

            def fetch_learn_state(self):
                return prim.fetch_learn_state()

        mono = run_learn(open_replica("mono"), _MonolithicPeer())
        streamer = open_replica("full")
        full = run_learn(streamer, prim)
        # the delta case: a small burst on the primary, then re-learn
        # the SAME learner — it already holds (almost) every SST
        burst = max(1, records // 100)
        for base in range(0, burst, per):
            kvs = [rpc_msg.KeyValue(b"d%08d" % i, value)
                   for i in range(base, min(base + per, burst))]
            prim.client_write(RPC_MULTI_PUT, rpc_msg.MultiPutRequest(
                hash_key=b"hd%04d" % (base % 97), kvs=kvs))
        prim.server.engine.flush()
        delta = run_learn(streamer, prim)

        # replay-only catch-up baseline: the same history applied
        # mutation by mutation through the prepare path
        replayer = open_replica("replay")
        t0 = time.perf_counter()
        window, replayed = [], 0
        for m in prim.plog.replay(0):
            window.append(m)
            replayed += 1
            if len(window) >= 64:
                replayer.on_prepare_batch(prim.ballot, window,
                                          window[-1].decree)
                window = []
        if window:
            replayer.on_prepare_batch(prim.ballot, window,
                                      window[-1].decree)
        replay = {"wall_s": round(time.perf_counter() - t0, 3),
                  "mutations": replayed}
        # NOTE the honest asymmetry: after plog GC only the tail is
        # replayable at all — this baseline exists because the primary
        # here still holds its full log
        ratio = round(mono["bytes"] / max(delta["bytes"], 1), 2)
        detail = {
            "records": records, "value_bytes": value_size,
            "load_s": round(load_s, 2),
            "monolithic": mono, "full_ship": full, "delta_ship": delta,
            "replay_catch_up": replay,
            "bytes_ratio_mono_over_delta": ratio,
            "host": {"start": host_start, "end": _host_info()},
        }
        if not (mono["digest_match"] and full["digest_match"]
                and delta["digest_match"]):
            _emit(_learn_degraded(
                "post-learn digest mismatch — a learn path lost bytes",
                detail=detail))
            return
        _emit({"metric": _learn_metric_name(), "value": ratio, "unit": "x",
               "vs_baseline": None, "detail": detail})
    finally:
        for r in reps:
            try:
                r.close()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def _offload_params():
    """(records, runs, value_size) for PEGASUS_BENCH_MODE=offload."""
    return (int(os.environ.get("PEGASUS_BENCH_OFFLOAD_RECORDS", 200_000)),
            4, int(os.environ.get("PEGASUS_BENCH_VALUE", 100)))


def _offload_metric_name() -> str:
    records, n_runs, value_size = _offload_params()
    return (f"compaction offload: remote-vs-local wall ratio "
            f"({records} records, {n_runs} runs, value={value_size}B)")


def _offload_degraded(reason: str, detail: dict = None) -> dict:
    d = {"degraded": True, "reason": reason}
    d.update(detail or {})
    return {"metric": _offload_metric_name(), "value": None, "unit": "x",
            "vs_baseline": None, "detail": d}


def offload_main():
    """PEGASUS_BENCH_MODE=offload: the rack-scale compaction-offload
    artifact (ISSUE 14) — the same merge run locally on cpu and through
    an in-process CompactOffloadService over real sockets, all on CPU
    (no TPU lease needed): wall clock for both lanes, bytes shipped and
    fetched, and the per-stage breakdown (offload.ship / offload.merge /
    offload.fetch spans). Byte identity between the lanes is asserted —
    a transfer that changes bytes must fail the bench, not report a
    speed — and a round the lane guard had to serve via the LOCAL cpu
    fallback reports a degraded line (the number would not be an offload
    measurement). One JSON line, learn-mode semantics."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    _enable_compile_cache()
    import shutil
    import tempfile

    from pegasus_tpu.ops.compact import CompactOptions, compact_blocks
    from pegasus_tpu.replication.compact_offload import (
        OFFLOAD_LANE_GUARD, CompactOffloadService, offload_compact_blocks)
    from pegasus_tpu.runtime.perf_counters import counters
    from pegasus_tpu.runtime.tracing import COMPACT_TRACER

    records, n_runs, value_size = _offload_params()
    host_start = _host_info()
    runs, fill_s = _fill(records, n_runs, value_size)
    opts = CompactOptions(backend="cpu", now=100, bottommost=True,
                          runs_sorted=True)
    tmp = tempfile.mkdtemp(prefix="pegasus_offload_bench_")
    svc = None
    try:
        t0 = time.perf_counter()
        local = compact_blocks(runs, opts)
        local_s = time.perf_counter() - t0
        local_digest = _out_digest(local.block)

        svc = CompactOffloadService(tmp, backend="cpu").start()
        OFFLOAD_LANE_GUARD.reset()

        def totals():
            return {k: counters.rate(f"offload.client.{k}").total()
                    for k in ("ship_bytes", "fetch_bytes", "ship_blocks",
                              "skipped_blocks")}

        before = totals()
        with COMPACT_TRACER.session() as sess:
            t0 = time.perf_counter()
            remote = offload_compact_blocks(runs, opts, svc.address,
                                            tenant="bench")
            offload_s = time.perf_counter() - t0
        after = totals()
        remote_digest = _out_digest(remote.block)
        lane = OFFLOAD_LANE_GUARD.state()
        detail = {
            "records": records, "n_runs": n_runs,
            "value_bytes": value_size, "fill_s": round(fill_s, 2),
            "local_compact_s": round(local_s, 3),
            "offload_compact_s": round(offload_s, 3),
            "shipped_bytes": after["ship_bytes"] - before["ship_bytes"],
            "fetched_bytes": after["fetch_bytes"] - before["fetch_bytes"],
            "shipped_runs": after["ship_blocks"] - before["ship_blocks"],
            "service": svc.status(),
            "lane": lane,
            "trace": sess.summary(),
            "host": {"start": host_start, "end": _host_info()},
        }
        if lane["fallbacks"]:
            # the guard served this merge via the LOCAL cpu path: the
            # wall number is not an offload measurement
            _emit(_offload_degraded(
                f"offload lane fell back to local cpu "
                f"({lane['last_fallback']})", detail=detail))
            return
        if remote_digest != local_digest:
            _emit(_offload_degraded(
                "offloaded output diverges from local compaction "
                f"(local {local_digest} vs remote {remote_digest})",
                detail=detail))
            return
        detail["byte_equal"] = True
        _emit({"metric": _offload_metric_name(),
               "value": round(offload_s / local_s, 3), "unit": "x",
               "vs_baseline": None, "detail": detail})
    finally:
        if svc is not None:
            svc.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def main():
    _arm_watchdog()
    n_total, n_runs, value_size, reps = _bench_params()

    # 1) fill + pack + CPU lane, all in-process, all pure numpy — the
    # parent never imports jax (see module docstring: lease discipline)
    from pegasus_tpu.engine.block import KVBlock
    from pegasus_tpu.ops.compact import CpuBackend, TpuBackend, pack_runs

    from pegasus_tpu.runtime.tracing import COMPACT_TRACER

    host_start = _host_info()
    runs, fill_s = _fill(n_total, n_runs, value_size)
    opts, fargs = _compact_opts()
    # the session turns the instrumented pipeline spans (pack / device /
    # gather) into the per-stage `trace` breakdown of the JSON detail —
    # summed over all reps (see `calls`), present even on degraded lines
    proc_t0 = time.process_time()
    with COMPACT_TRACER.session() as cpu_sess:
        packed = pack_runs(runs, opts, need_sbytes=True)
        concat = KVBlock.concat(runs)
        n_in = sum(packed.lens)
        cpu_s, cpu_out, cpu_split = _lane(CpuBackend(), packed, concat,
                                          fargs, reps)
    cpu_process_s = time.process_time() - proc_t0
    cpu_digest = _out_digest(cpu_out)
    global _CPU_DETAIL
    cpu_detail = _CPU_DETAIL = {
        "fill_s": round(fill_s, 3),
        "cpu_compact_s": round(cpu_s, 3),
        "cpu_split": cpu_split,
        "cpu_records_per_s": int(n_in / cpu_s),
        # process cpu-seconds across pack+lane vs their wall time: the
        # contention tell for an unexplained cpu-lane regression
        "cpu_process_s": round(cpu_process_s, 3),
        "input_records": n_in,
        "output_records": cpu_digest["n_out"],
        "trace": cpu_sess.summary(),
        "host": {"start": host_start, "end": _host_info()},
    }

    # 2) TPU lane
    if os.environ.get("PEGASUS_BENCH_ASSUME_TPU") == "1":
        # in-process caller (tools/tpu_oneshot.py) already holds the live
        # lease in THIS process; a child would starve on it
        _enable_compile_cache()
        import jax

        platform = str(jax.devices()[0])
        backend = TpuBackend()
        prep = backend.prepare(packed)
        tpu_s, tpu_out, tpu_split = _tpu_lanes(backend, prep, concat, fargs,
                                               reps)
        from pegasus_tpu.runtime.lane_guard import LANE_GUARD

        lane_result = {"tpu_s": tpu_s, "split": tpu_split,
                       "platform": platform, "lane": LANE_GUARD.state()}
        lane_result.update(_out_digest(tpu_out))
        reason = ""
    else:
        # free the parent's copies before the child builds its own: peak
        # RSS stays one-process-sized on this small box
        del runs, packed, concat, cpu_out
        lane_timeout = float(os.environ.get("PEGASUS_BENCH_LANE_S", 360))
        lane_result, reason = _run_tpu_lane_child(lane_timeout)

    if lane_result is None:
        print(f"bench: TPU lane unavailable ({reason}); reporting the cpu "
              "lane as a degraded result.", file=sys.stderr, flush=True)
        detail = dict(cpu_detail)
        if _LANE_STATE.get("wedge_status"):
            # the abandoned child's last heartbeat: stage attribution for
            # the wedge (last_ok / wedged_at_stage / open stages) plus the
            # lane guard's fallback/retry/breaker totals
            detail["watchdog"] = _LANE_STATE["wedge_status"]
            if _LANE_STATE["wedge_status"].get("lane") is not None:
                detail["lane"] = _LANE_STATE["wedge_status"]["lane"]
        _emit(_degraded(n_total, n_runs, value_size, reason, detail=detail))
        return

    assert lane_result["n_out"] == cpu_digest["n_out"], \
        "backend outputs diverge in count"
    assert lane_result["key_sha"] == cpu_digest["key_sha"], "key bytes diverge"
    assert lane_result["val_sha"] == cpu_digest["val_sha"], "value bytes diverge"

    tpu_s = lane_result["tpu_s"]
    speedup = cpu_s / tpu_s
    detail = dict(cpu_detail)
    detail.update({
        "tpu_compact_s": round(tpu_s, 3),
        "tpu_split": lane_result["split"],
        "tpu_records_per_s": int(n_in / tpu_s),
        "byte_equal": True,
        "platform": lane_result["platform"],
        # fallbacks/retries/breaker trips recorded by the child's lane
        # guard — BENCH_r06+ readers must check these before trusting the
        # speedup as a true device number
        "lane": lane_result.get("lane"),
    })
    _emit({
        "metric": _metric_name(n_total, n_runs, value_size),
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup, 3),
        "detail": detail,
    })


if __name__ == "__main__":
    if "--tpu-lane" in sys.argv:
        tpu_lane_main()
        sys.exit(0)
    _mode = os.environ.get("PEGASUS_BENCH_MODE", "")
    try:
        if _mode == "ycsb":
            _arm_watchdog()
            ycsb_main()
        elif _mode == "learn":
            _arm_watchdog()
            learn_main()
        elif _mode == "offload":
            _arm_watchdog()
            offload_main()
        elif _mode == "native":
            _arm_watchdog()
            native_main()
        else:
            main()
    except Exception as e:  # noqa: BLE001 - the driver needs a JSON line, always
        import traceback

        traceback.print_exc()
        if not _RESULT_PRINTED:
            if _mode == "ycsb":
                _emit(_ycsb_degraded(f"bench crashed: {e!r}"))
            elif _mode == "learn":
                _emit(_learn_degraded(f"bench crashed: {e!r}"))
            elif _mode == "offload":
                _emit(_offload_degraded(f"bench crashed: {e!r}"))
            elif _mode == "native":
                _emit(_native_degraded(f"bench crashed: {e!r}"))
            else:
                n_total, n_runs, value_size, _ = _bench_params()
                _emit(_degraded(n_total, n_runs, value_size,
                                f"bench crashed: {e!r}", detail=_CPU_DETAIL))
        sys.exit(0)
