#!/usr/bin/env python
"""Thin CLI shim over tools/analyze/fail_points.py (the fail-point
cross-check now lives in the shared static-analysis framework; run
`python -m tools.analyze` for the whole plane). Kept so existing
invocations — tests/test_lane_guard.py runs this script — and the
historical `run_lint()` surface keep working."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.analyze import Repo  # noqa: E402
from tools.analyze import fail_points as _pass  # noqa: E402

_REPO = Repo()


def source_points() -> set:
    return _pass.source_points(_REPO)


def test_local_points() -> set:
    return _pass.test_local_points(_REPO)


def test_armed_points() -> set:
    return _pass.test_armed_points(_REPO)


def run_lint() -> list:
    """-> list of error strings (empty = clean). Reads the collectors
    through THIS module so monkeypatched tests keep their teeth."""
    src = source_points()
    armed = test_armed_points()
    hooks = src | test_local_points()
    return [f.message for f in
            _pass.lint_findings(src, armed, hooks, _REPO.readme)]


def main() -> int:
    errors = run_lint()
    for e in errors:
        print(f"check_fail_points: {e}", file=sys.stderr)
    if not errors:
        print(f"check_fail_points: OK "
              f"({len(source_points())} source hooks, "
              f"{len(test_armed_points())} test-armed names)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
