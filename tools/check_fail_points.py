#!/usr/bin/env python
"""Fail-point cross-check lint (wired into the test run via
tests/test_lane_guard.py):

  1. every fail-point name ARMED in tests (``cfg("name", ...)``) must
     exist as a hook in source (``fail_point("name")`` / ``inject(...)``/
     ``_fail(...)`` / ``_inject(...)``) — a test arming a point that no
     code evaluates silently tests nothing;
  2. every fail-point hook in source must be DOCUMENTED in README.md
     (the Robustness section's fail-point table) — chaos hooks nobody can
     discover rot.

Dynamic names (``fail_point(f"rpc.{code}")``) become prefix wildcards
(``rpc.*``): a test may arm any name under the prefix, and the README
must mention the prefix.
"""

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

_CALL_RE = re.compile(
    r"\b(?:fail_point|_fail|inject|_inject|_stage_fail)\(\s*(f?)\"([^\"]+)\"")
_CFG_RE = re.compile(r"\bcfg\(\s*\"([^\"]+)\"")


def _points_in(files) -> set:
    names = set()
    for p in files:
        text = p.read_text()
        for m in _CALL_RE.finditer(text):
            name = m.group(2)
            if m.group(1):  # f-string: every {expr} hole becomes a wildcard
                name = re.sub(r"\{[^}]*\}", "*", name)
            names.add(name)
    return names


def source_points() -> set:
    return _points_in(list((REPO / "pegasus_tpu").rglob("*.py"))
                      + [REPO / "bench.py"])


def test_local_points() -> set:
    """Hooks evaluated INSIDE tests (the fail-point mini-language unit
    tests arm and evaluate throwaway names like 'p1' in the same file) —
    legitimate, but they need no README documentation."""
    return _points_in((REPO / "tests").rglob("*.py"))


def test_armed_points() -> set:
    names = set()
    for p in (REPO / "tests").rglob("*.py"):
        names.update(_CFG_RE.findall(p.read_text()))
    return names


def _matches(name: str, source: set) -> bool:
    if name in source:
        return True
    return any(s.endswith("*") and name.startswith(s[:-1])
               for s in source)


def run_lint() -> list:
    """-> list of error strings (empty = clean)."""
    src = source_points()
    armed = test_armed_points()
    hooks = src | test_local_points()
    readme = (REPO / "README.md").read_text()
    errors = []
    for name in sorted(armed):
        if not _matches(name, hooks):
            errors.append(
                f"tests arm fail point {name!r} but no source hook "
                f"evaluates it (known: {sorted(hooks)})")
    for name in sorted(src):
        probe = name.split("*")[0] if "*" in name else name
        if probe not in readme:
            errors.append(
                f"source fail point {name!r} is undocumented — add it to "
                f"README.md's Robustness fail-point table")
    return errors


def main() -> int:
    errors = run_lint()
    for e in errors:
        print(f"check_fail_points: {e}", file=sys.stderr)
    if not errors:
        print(f"check_fail_points: OK "
              f"({len(source_points())} source hooks, "
              f"{len(test_armed_points())} test-armed names)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
