#!/usr/bin/env python
"""Thin CLI shim over tools/analyze/remote_commands.py (the
remote-command cross-check now lives in the shared static-analysis
framework; run `python -m tools.analyze` for the whole plane). Kept so
existing invocations — tests/test_tools.py runs this script and
monkeypatches `source_commands` — keep working."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.analyze import Repo  # noqa: E402
from tools.analyze import remote_commands as _pass  # noqa: E402

_REPO = Repo()


def source_commands() -> set:
    return _pass.source_commands(_REPO)


def readme_command_rows() -> list:
    return _pass.readme_command_rows(_REPO)


def run_lint() -> list:
    """-> list of error strings (empty = clean). Reads the collectors
    through THIS module so monkeypatched tests keep their teeth."""
    return [f.message for f in
            _pass.lint_findings(source_commands(), readme_command_rows())]


def main() -> int:
    errors = run_lint()
    for e in errors:
        print(f"check_remote_commands: {e}", file=sys.stderr)
    if not errors:
        print(f"check_remote_commands: OK "
              f"({len(source_commands())} registered commands)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
