#!/usr/bin/env python
"""Remote-command cross-check lint (wired into the test run via
tests/test_tools.py), the admin-surface twin of check_fail_points.py /
check_metric_names.py:

every remote command registered in source
(``commands.register("name", ...)`` on a RemoteCommandService, or
``self.register("name", ...)`` inside runtime/remote_command.py's
register_defaults) must be DOCUMENTED in README.md's
'### Remote-command table' — admin commands nobody can discover rot, and
an operator runbook pointing at a renamed command silently breaks.

The REVERSE direction is linted too: every row of the README table must
still name a registered command — a row for a deleted command documents
an admin surface no node will ever answer.
"""

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# RemoteCommandService registrations: `<...>commands.register("name"` in
# any source file, plus `self.register("name"` in remote_command.py itself
# (register_defaults). Deliberately NOT a bare `.register(` — RpcServer
# task-code registrations share that shape.
_CMDS_RE = re.compile(r"\bcommands\.register\(\s*\"([^\"]+)\"")
_SELF_RE = re.compile(r"\bself\.register\(\s*\"([^\"]+)\"")


def source_commands() -> set:
    names = set()
    for p in (REPO / "pegasus_tpu").rglob("*.py"):
        text = p.read_text()
        names.update(_CMDS_RE.findall(text))
        if p.name == "remote_command.py":
            names.update(_SELF_RE.findall(text))
    return names


def readme_command_rows() -> list:
    """Command names from README's '### Remote-command table' section:
    each row's first backticked token (the rest of the span is usage —
    parsed from the whole line, not a naive '|' cell split, because
    usage strings legitimately contain escaped `\\|` alternations)."""
    text = (REPO / "README.md").read_text()
    m = re.search(r"^### Remote-command table$(.*?)^## ", text,
                  re.MULTILINE | re.DOTALL)
    section = m.group(1) if m else ""
    rows = []
    for line in section.splitlines():
        if not line.startswith("| `"):
            continue  # header / separator / prose
        first = re.search(r"`([^`\s]+)", line)
        if first:
            rows.append(first.group(1))
    return rows


def run_lint() -> list:
    """-> list of error strings (empty = clean)."""
    src = source_commands()
    rows = readme_command_rows()
    errors = []
    if not rows:
        return ["README.md has no '### Remote-command table' section "
                "(or it is empty) — every registered remote command must "
                "be documented there"]
    documented = set(rows)
    for name in sorted(src):
        if name not in documented:
            errors.append(
                f"remote command {name!r} is registered in source but "
                "missing from README.md's Remote-command table")
    for name in sorted(documented):
        if name not in src:
            errors.append(
                f"README Remote-command table row {name!r} has no matching "
                "registration in source — delete the row or restore the "
                "command")
    return errors


def main() -> int:
    errors = run_lint()
    for e in errors:
        print(f"check_remote_commands: {e}", file=sys.stderr)
    if not errors:
        print(f"check_remote_commands: OK "
              f"({len(source_commands())} registered commands)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
