"""Engine-level compaction benchmark: LsmEngine.manual_compact, cpu vs tpu.

The SYSTEM number (VERDICT-r2 item 4), distinct from bench.py's kernel
number: wall-clock of a full manual compaction through the real engine —
SST loads, the device-resident run cache (backend=tpu packs+uploads each
file once, then merges read HBM), merge/dedup/filter, output-file split,
manifest swap. Mirrors the reference's pegasus_manual_compact timing over
a filled table (scripts/pegasus_manual_compact.sh flow).

Usage:
    python tools/engine_bench.py            # all lanes, default sizes
    PEGASUS_EBENCH_N=2000000 PEGASUS_EBENCH_BACKENDS=tpu python tools/...

Lanes (PEGASUS_EBENCH_BACKENDS, default "cpu,tpu,tpu_dv"): cpu, tpu
(host-gather materialization), tpu_dv (EngineOptions.device_values —
output values materialize on device; the measurement that decides
whether the flag defaults on). Prints one JSON line per lane + a final
comparison line of cpu vs the best tpu lane.

Bounded (VERDICT-r3 item 8): a watchdog hard-exits with a degraded JSON
line after PEGASUS_EBENCH_TIMEOUT_S (default 1200 s) carrying whatever
lanes completed — a wedged tunnel mid-backend-init can stall the tpu
lanes forever, and no tool may be able to hang its caller.
PEGASUS_EBENCH_FAKE=sleep simulates that wedge (tests).
"""

import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

_RESULTS = {}  # lanes completed so far (the watchdog reports them)
_PRINTED_FINAL = False


def _arm_watchdog():
    import threading

    budget = int(os.environ.get("PEGASUS_EBENCH_TIMEOUT_S", 1200))
    if budget <= 0:
        return

    def boom():
        if not _PRINTED_FINAL:
            print(json.dumps({
                "metric": "engine manual_compact speedup tpu vs cpu",
                "value": None, "unit": "x", "degraded": True,
                "reason": f"watchdog fired after {budget}s",
                "completed_lanes": {k: v.get("manual_compact_s")
                                    for k, v in _RESULTS.items()},
            }), flush=True)
        os._exit(0)

    t = threading.Timer(budget, boom)
    t.daemon = True
    t.start()


def build_table(path: str, backend: str, n: int, value_size: int,
                n_files: int, device_values: bool = False):
    """Fill a table: n records across n_files L0 SSTs with overlapping
    hashkeys (dedup work exists), no auto-compaction."""
    from bench import make_run, presort_run
    from pegasus_tpu.engine import EngineOptions, LsmEngine
    from pegasus_tpu.engine.sstable import SSTable, write_sst

    opts = EngineOptions(backend=backend, l0_compaction_trigger=1 << 30,
                         level_base_bytes=1 << 62,
                         device_values=device_values)
    eng = LsmEngine(path, opts)
    per = n // n_files
    for s in range(n_files):
        blk = presort_run(make_run(per, value_size, seed=s,
                                   key_space=max(1, n // 2)))
        with eng._lock:
            name = eng._alloc_file_locked()
        write_sst(os.path.join(path, name), blk,
                  {"level": 0, "last_flushed_decree": s + 1})
        sst = SSTable(os.path.join(path, name))
        sst._block = blk
        if backend == "tpu":
            # flush-time residency prime (values too when the lane says so)
            sst.device_run(opts.prefix_u32, with_values=device_values)
        with eng._lock:
            eng._l0.insert(0, sst)
            eng._write_manifest_locked()
    return eng


def run_lane(lane: str, root: str, n: int, value_size: int,
             n_files: int, reps: int) -> dict:
    backend = "tpu" if lane.startswith("tpu") else "cpu"
    device_values = lane == "tpu_dv"
    path = os.path.join(root, lane)
    shutil.rmtree(path, ignore_errors=True)
    t0 = time.perf_counter()
    eng = build_table(path, backend, n, value_size, n_files, device_values)
    fill_s = time.perf_counter() - t0
    best = float("inf")
    stats = {}
    for rep in range(reps):
        if rep > 0:
            # rebuild the L0 state so every rep compacts the same input
            eng.close()
            shutil.rmtree(path, ignore_errors=True)
            eng = build_table(path, backend, n, value_size, n_files,
                              device_values)
        t0 = time.perf_counter()
        stats = eng.manual_compact(now=100)
        best = min(best, time.perf_counter() - t0)
    digest = table_digest(eng)
    eng.close()
    return {"backend": lane, "fill_s": round(fill_s, 3),
            "manual_compact_s": round(best, 3),
            "records_per_s": int(stats.get("input_records", n) / best),
            "stats": stats, "digest": digest}


def table_digest(eng) -> str:
    """Order-sensitive digest over every output record (byte-equality
    check between lanes)."""
    import hashlib

    h = hashlib.sha256()
    with eng._lock:
        files = list(eng._l0) + [f for lv in sorted(eng._levels)
                                 for f in eng._levels[lv]]
    for sst in files:
        b = sst.block()
        h.update(b.key_arena.tobytes())
        h.update(b.val_arena.tobytes())
    return h.hexdigest()[:16]


def main():
    global _PRINTED_FINAL
    _arm_watchdog()
    n = int(os.environ.get("PEGASUS_EBENCH_N", 2_000_000))
    value_size = int(os.environ.get("PEGASUS_EBENCH_VALUE", 100))
    n_files = int(os.environ.get("PEGASUS_EBENCH_FILES", 4))
    reps = int(os.environ.get("PEGASUS_EBENCH_REPS", 2))
    backends = os.environ.get("PEGASUS_EBENCH_BACKENDS",
                              "cpu,tpu,tpu_dv").split(",")
    root = os.environ.get("PEGASUS_EBENCH_DIR", "/tmp/pegasus_engine_bench")
    if any(b.startswith("tpu") for b in backends):
        if os.environ.get("PEGASUS_EBENCH_FAKE") == "sleep":
            time.sleep(3600)  # test hook: backend init wedges
        import jax

        from pegasus_tpu.base.utils import enable_compile_cache

        if os.environ.get("JAX_PLATFORMS") == "cpu":
            # the image re-asserts the axon platform over the env var; the
            # config API wins over both (matches bench.py / tests/conftest)
            jax.config.update("jax_platforms", "cpu")
        enable_compile_cache(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    results = _RESULTS
    for backend in backends:
        results[backend] = run_lane(backend, root, n, value_size, n_files,
                                    reps)
        print(json.dumps(results[backend]), flush=True)
    tpu_lanes = [k for k in results if k.startswith("tpu")]
    if "cpu" in results and tpu_lanes:
        best = min(tpu_lanes, key=lambda k: results[k]["manual_compact_s"])
        cmp = {
            "metric": f"engine manual_compact speedup tpu vs cpu ({n} records)",
            "value": round(results["cpu"]["manual_compact_s"]
                           / results[best]["manual_compact_s"], 3),
            "unit": "x",
            "best_lane": best,
            "byte_equal": all(results["cpu"]["digest"] == results[k]["digest"]
                              for k in tpu_lanes),
        }
        print(json.dumps(cmp), flush=True)
    _PRINTED_FINAL = True
    shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
