"""Incremental device-pipeline timing for the axon tunnel backend, where
block_until_ready does not actually block: every measurement is forced by
downloading one element of the result, and stage costs come from the
difference between successive prefixes of the pipeline.

    python tools/profile_pipeline2.py [N]
"""

import functools
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def force(x):
    """Block until x is computed by downloading one element."""
    leaf = x
    while isinstance(leaf, (tuple, list)):
        leaf = leaf[0]
    return np.asarray(leaf[:1] if getattr(leaf, "ndim", 0) else leaf)


def timed(label, fn, *args, reps=2):
    out = fn(*args)
    force(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        force(out)
        best = min(best, time.perf_counter() - t0)
    print(f"{label}: {best:.3f}s", flush=True)
    return best, out


def main():
    n_total = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    from pegasus_tpu.base.utils import enable_compile_cache

    enable_compile_cache(REPO)
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the image re-asserts the axon platform over the env var
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import bench as B
    from pegasus_tpu.ops.compact import (CompactOptions, TpuBackend,
                                         pack_runs, _pow2ceil)
    from pegasus_tpu.ops.device_sort import merge_two_sorted

    print("platform:", jax.devices()[0], flush=True)
    n_runs = 4
    per = n_total // n_runs
    runs = [B.presort_run(B.make_run(per, 100, seed=s,
                                     key_space=max(1, n_total // 2)))
            for s in range(n_runs)]
    opts = CompactOptions(backend="tpu", now=100, bottommost=True,
                          runs_sorted=True)
    packed = pack_runs(runs, opts, need_sbytes=True)
    backend = TpuBackend()
    prep = backend.prepare(packed)
    force(prep.run_cols[0][0])  # uploads done
    nk = prep.w + (2 if prep.has_rank else 1)
    print("prep uploaded", flush=True)

    def tree(run_cols, aux_runs):
        # mirrors _pipeline_body's pre-merge filter fold (r3): TTL/tomb
        # bits drop into the idx column elementwise before the merge
        items = []
        for i, rc in enumerate(run_cols):
            *kcols, klen, idx = rc
            expire, deleted, _hash32 = aux_runs[i]
            filt = ((expire > 0) & (expire <= jnp.uint32(100))) | deleted
            idx = jnp.where(filt, np.int32(-1), idx)
            kp = (klen << jnp.uint32(8)) | jnp.uint32(i)
            items.append((prep.padded_lens[i], list(kcols) + [kp, idx]))
        pad_fill = tuple([0xFFFFFFFF] * nk + [np.int32(-1)])
        while len(items) > 1:
            items.sort(key=lambda x: x[0])
            (la, a), (lb, b) = items[0], items[1]
            merged = merge_two_sorted(a, b, nk, pad_fill)
            lm = _pow2ceil(la + lb)
            if lm > la + lb:
                merged = [c[: la + lb] for c in merged]
            items = items[2:] + [(la + lb, merged)]
        return items[0][1]

    def mask_of(cols):
        # post-merge work is dedup-only since the r3 pre-merge fold
        idx = cols[-1]
        kp = cols[nk - 1]
        key_eq = cols[: nk - 1] + [kp >> jnp.uint32(8)]
        same_tail = functools.reduce(
            jnp.logical_and, [c[1:] == c[:-1] for c in key_eq])
        same = jnp.concatenate([jnp.zeros(1, dtype=bool), same_tail])
        return (idx >= 0) & ~same

    def p1(run_cols, aux):
        return tree(run_cols, aux)[-1]

    def p2(run_cols, aux):
        cols = tree(run_cols, aux)
        return mask_of(cols), cols[-1]

    def p3(run_cols, aux):
        cols = tree(run_cols, aux)
        keep = mask_of(cols)
        idx = cols[-1]
        n = idx.shape[0]
        pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        tgt = jnp.where(keep, pos, n)
        out = jnp.full((n,), -1, jnp.int32).at[tgt].set(idx, mode="drop")
        return out, pos[-1] + 1

    t1, _ = timed("p1 fold+merge tree", jax.jit(p1), prep.run_cols, prep.aux)
    t2, _ = timed("p2 +dedup mask", jax.jit(p2), prep.run_cols, prep.aux)
    t3, o3 = timed("p3 +cumsum+scatter", jax.jit(p3),
                   prep.run_cols, prep.aux)
    print(f"  => mask {t2-t1:.3f}s, scatter-part {t3-t2:.3f}s", flush=True)
    o3h = o3
    cnt = int(np.asarray(o3[1]))

    t0 = time.perf_counter()
    _ = np.asarray(o3h[0][:cnt])
    print(f"index download {cnt*4/1e6:.0f}MB: {time.perf_counter()-t0:.3f}s",
          flush=True)


if __name__ == "__main__":
    main()
