"""pegasus_bench: db_bench-style op lanes through the serving stack.

The reference harness shape (src/test/bench_test/benchmark.cpp:210-215 +
scripts/pegasus_bench_run.sh:25-44): named benchmarks run in sequence over
a shared table, each reporting QPS + avg + P99 latency per thread count.

    python tools/pegasus_bench.py --benchmarks fillseq_pegasus,\
fillrandom_pegasus,readrandom_pegasus,deleterandom_pegasus \
        --num 10000 --threads 1,4 --value-size 1000 [--meta host:port]

(no --meta: boots an in-process onebox). One JSON line per (benchmark,
thread-count), mirroring pegasus_bench_run.sh's thread sweep.
"""

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


KNOWN_BENCHMARKS = ("scan_pegasus", "multisetrandom_pegasus",
                    "multigetrandom_pegasus",
                    "fillseq_pegasus", "fillrandom_pegasus",
                    "readrandom_pegasus", "deleterandom_pegasus")


def run_lane(name, meta_addr, table, n_per_thread, n_threads, value_size):
    from pegasus_tpu.client import MetaResolver, PegasusClient, PegasusError

    value = bytes(value_size)
    errors = [0] * n_threads
    lats = [[] for _ in range(n_threads)]

    def op_fn(cli, tid, rng):
        if name == "fillseq_pegasus":
            seq = [0]

            def op():
                i = seq[0]
                seq[0] += 1
                cli.set(b"bk%02d%08d" % (tid, i), b"s", value)
        elif name == "fillrandom_pegasus":
            def op():
                cli.set(b"bk%02d%08d" % (tid, rng.randrange(n_per_thread)),
                        b"s", value)
        elif name == "readrandom_pegasus":
            def op():
                cli.get(b"bk%02d%08d" % (tid, rng.randrange(n_per_thread)),
                        b"s")
        elif name == "deleterandom_pegasus":
            def op():
                cli.delete(b"bk%02d%08d" % (tid, rng.randrange(n_per_thread)),
                           b"s")
        elif name == "multisetrandom_pegasus":
            # reference pegasus_bench multi_set: 10 sortkeys per op under
            # one hash key (one batched write RPC / one decree)
            def op():
                hk = b"mk%02d%06d" % (tid, rng.randrange(n_per_thread))
                cli.multi_set(hk, {b"s%02d" % i: value for i in range(10)})
        elif name == "multigetrandom_pegasus":
            def op():
                hk = b"mk%02d%06d" % (tid, rng.randrange(n_per_thread))
                cli.multi_get(hk)
        else:
            raise ValueError(f"unknown benchmark {name}")
        return op

    # clients (meta resolution included) are built BEFORE the clock starts:
    # boot-up RPCs must not deflate small runs' QPS
    clients = [PegasusClient(MetaResolver([meta_addr], table), timeout=15)
               for _ in range(n_threads)]

    def worker(tid):
        rng = random.Random(tid * 7919)
        cli = clients[tid]
        op = op_fn(cli, tid, rng)
        for _ in range(n_per_thread):
            t0 = time.perf_counter()
            try:
                op()
            except PegasusError:
                errors[tid] += 1
            lats[tid].append((time.perf_counter() - t0) * 1e6)

    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t_start
    for cli in clients:
        cli.close()
    flat = sorted(x for lane in lats for x in lane)
    total = len(flat)
    return {
        "benchmark": name, "threads": n_threads,
        "qps": round(total / elapsed, 1),
        "avg_us": round(sum(flat) / max(1, total), 1),
        "p99_us": round(flat[min(total - 1, int(total * 0.99))], 1) if flat else 0,
        "ops": total, "errors": sum(errors),
        "value_size": value_size,
    }


def run_scan_lane(meta_addr, table, n_threads):
    """Full-table scan throughput (the copy_data / backup / bulk-export
    shape, reference scan_data in pegasus_bench): every partition's
    unordered scanner drained, split over n_threads."""
    from pegasus_tpu.client import MetaResolver, PegasusClient

    cli = PegasusClient(MetaResolver([meta_addr], table), timeout=15)
    scanners = cli.get_unordered_scanners()
    counts = [0] * n_threads
    lock = threading.Lock()
    queue = list(scanners)

    def worker(tid):
        while True:
            with lock:
                if not queue:
                    return
                sc = queue.pop()
            for _ in sc:
                counts[tid] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    cli.close()
    total = sum(counts)
    return {"benchmark": "scan_pegasus", "threads": n_threads,
            "qps": round(total / elapsed, 1), "ops": total,
            "errors": 0, "elapsed_s": round(elapsed, 2)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--meta", default="")
    ap.add_argument("--table", default="bench")
    ap.add_argument("--benchmarks",
                    default="fillseq_pegasus,fillrandom_pegasus,"
                            "readrandom_pegasus,deleterandom_pegasus")
    ap.add_argument("--num", type=int, default=10_000)
    ap.add_argument("--threads", default="1")
    ap.add_argument("--value-size", type=int, default=1000)
    ap.add_argument("--partitions", type=int, default=8)
    args = ap.parse_args()

    names = [n.strip() for n in args.benchmarks.split(",") if n.strip()]
    unknown = [n for n in names if n not in KNOWN_BENCHMARKS]
    if unknown:
        # fail LOUDLY before any thread spawns: a typo must not emit a
        # plausible-looking zero-QPS JSON line with exit code 0
        print(f"unknown benchmark(s) {unknown}; known: "
              f"{', '.join(KNOWN_BENCHMARKS)}", file=sys.stderr)
        sys.exit(2)
    from tools._onebox import resolve_cluster

    meta_addr, box = resolve_cluster(args.meta, args.table, args.partitions)
    try:
        for n_threads in (int(t) for t in args.threads.split(",")):
            for name in names:
                if name == "scan_pegasus":
                    out = run_scan_lane(meta_addr, args.table, n_threads)
                else:
                    out = run_lane(name, meta_addr, args.table,
                                   args.num, n_threads, args.value_size)
                print(json.dumps(out), flush=True)
    finally:
        if box is not None:
            box.stop()


if __name__ == "__main__":
    main()
