"""Single-process, single-lease TPU validation + benchmark session.

Why this exists: the axon tunnel hands out ONE device lease, and (as
observed live on 2026-07-29) the lease is not always released when a
client process exits — the FIRST client after a long-idle period gets
in, every later backend init sleeps in the plugin's retry loop until
some long server-side timeout. tools/tpu_session.py's design (a fresh
subprocess per stage) is therefore exactly wrong on this tunnel: stage 1
(probe) consumed the day's lease and stages 2+ starved.

This script makes ONE connection and never lets it go until every stage
is done, in-process:

  1. init      — jax.devices() (blocks however long the lease takes;
                 run under a parent timeout, never SIGKILL)
  2. kernels   — small-N byte-equality cpu vs tpu (xla network path and
                 cached-device-run path)
  3. pallas    — toggle PEGASUS_PALLAS in-process (clearing the compiled
                 pipeline caches), same equality check
  4. bench     — bench.py main() in-process at PEGASUS_BENCH_N
                 (PEGASUS_BENCH_ASSUME_TPU=1 skips its subprocess probe),
                 with pallas off, then on if stage 3 passed
  5. engine    — tools/engine_bench.py main() in-process

Progress appends to TPU_SESSION.log after every stage so a mid-session
tunnel death still leaves completed stages recorded.

Usage: python tools/tpu_oneshot.py [--stages init,kernels,pallas,bench,engine]
"""

import argparse
import io
import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
LOG = os.path.join(REPO, "TPU_SESSION.log")


def log(line: str):
    stamp = time.strftime("%Y-%m-%d %H:%M:%S")
    with open(LOG, "a") as f:
        f.write(f"[{stamp}] oneshot: {line}\n")
    print(f"[{stamp}] {line}", flush=True)


def _clear_pipeline_caches():
    from pegasus_tpu.ops import compact

    compact._compiled_pipeline.cache_clear()
    compact._compiled_pipeline_cached.cache_clear()


def _kernel_equality() -> bool:
    """Small-N byte-equality: cpu vs tpu (host-packed) vs cached device
    runs, under whatever PEGASUS_PALLAS currently says."""
    import numpy as np

    import tests.test_compact_ops as t
    from pegasus_tpu.ops.compact import (CompactOptions, compact_blocks,
                                         pack_run_device, sort_block)

    rng = np.random.default_rng(5)
    recs = [(b"u%05d" % rng.integers(0, 300), b"s%d" % (i % 5),
             b"v%d" % i, 0, bool(rng.random() < .1)) for i in range(3000)]
    runs = [sort_block(t.make_block(p), CompactOptions(backend="cpu"))
            for p in (recs[:1500], recs[1500:])]
    o = dict(now=100, bottommost=True, runs_sorted=True)
    cpu = compact_blocks(runs, CompactOptions(backend="cpu", **o))
    tpu = compact_blocks(runs, CompactOptions(backend="tpu", **o))
    drs = [pack_run_device(b) for b in runs]
    cch = compact_blocks(runs, CompactOptions(backend="tpu", **o),
                         device_runs=drs)
    for x in (tpu, cch):
        assert np.array_equal(cpu.block.key_arena, x.block.key_arena)
        assert np.array_equal(cpu.block.val_arena, x.block.val_arena)
    return True


def stage_init() -> bool:
    import jax

    from pegasus_tpu.base.utils import enable_compile_cache

    t0 = time.time()
    log("init: acquiring backend (a wedged tunnel sleeps here; the plugin "
        "gives up with UNAVAILABLE after ~25 min)")
    dev = jax.devices()[0]
    import jax.numpy as jnp

    assert int(jnp.arange(64).sum()) == 2016
    enable_compile_cache(REPO)
    log(f"init: lease acquired after {time.time() - t0:.1f}s — {dev}")
    return True


def stage_kernels() -> bool:
    os.environ.pop("PEGASUS_PALLAS", None)
    t0 = time.time()
    ok = _kernel_equality()
    log(f"kernels(xla+cached): BYTE_EQUAL in {time.time() - t0:.1f}s")
    return ok


def stage_pallas() -> bool:
    os.environ["PEGASUS_PALLAS"] = "1"
    _clear_pipeline_caches()
    t0 = time.time()
    try:
        ok = _kernel_equality()
        log(f"pallas: BYTE_EQUAL in {time.time() - t0:.1f}s")
        return ok
    except Exception as e:  # noqa: BLE001 - record, fall back, continue
        log(f"pallas: FAILED on hardware after {time.time() - t0:.1f}s: "
            f"{type(e).__name__}: {str(e)[:300]}")
        for ln in traceback.format_exc().splitlines()[-8:]:
            log(f"  pallas-tb: {ln}")
        return False
    finally:
        os.environ.pop("PEGASUS_PALLAS", None)
        _clear_pipeline_caches()


def _run_bench(tag: str):
    import bench

    buf = io.StringIO()
    real = sys.stdout
    t0 = time.time()
    try:
        sys.stdout = buf
        bench.main()
    finally:
        sys.stdout = real
        bench._RESULT_PRINTED = False
    for line in buf.getvalue().strip().splitlines():
        log(f"bench[{tag}]: {line}")
    log(f"bench[{tag}]: done in {time.time() - t0:.1f}s")


def stage_bench(pallas_ok: bool):
    os.environ.setdefault("PEGASUS_BENCH_N", "10000000")
    os.environ["PEGASUS_BENCH_ASSUME_TPU"] = "1"
    os.environ["PEGASUS_BENCH_TIMEOUT_S"] = "0"  # parent owns the watchdog
    os.environ.pop("PEGASUS_PALLAS", None)
    _run_bench("xla")
    if pallas_ok:
        os.environ["PEGASUS_PALLAS"] = "1"
        _clear_pipeline_caches()
        try:
            _run_bench("pallas")
        finally:
            os.environ.pop("PEGASUS_PALLAS", None)
            _clear_pipeline_caches()


def stage_engine():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import engine_bench

    os.environ.setdefault("PEGASUS_EBENCH_TIMEOUT_S", "0")  # parent bounds
    buf = io.StringIO()
    real = sys.stdout
    t0 = time.time()
    try:
        sys.stdout = buf
        engine_bench.main()
    finally:
        sys.stdout = real
    for line in buf.getvalue().strip().splitlines():
        log(f"engine: {line}")
    log(f"engine: done in {time.time() - t0:.1f}s")


def stage_scale():
    """North-star scale ON CHIP, same lease: the blockwise
    bigger-than-device compaction at PEGASUS_SCALE_N (default here 100M,
    ~14 GB of input arenas — the v5e merge columns fit per 16M-record
    range block; values stay host-side on this lane)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import scale_bench

    os.environ.setdefault("PEGASUS_SCALE_N", "100000000")
    os.environ.setdefault("PEGASUS_SCALE_TIMEOUT_S", "0")  # parent bounds
    buf = io.StringIO()
    real = sys.stdout
    t0 = time.time()
    try:
        sys.stdout = buf
        scale_bench.main()
    finally:
        sys.stdout = real
        scale_bench._PRINTED = False
    for line in buf.getvalue().strip().splitlines():
        log(f"scale: {line}")
    log(f"scale: done in {time.time() - t0:.1f}s")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", default="init,kernels,pallas,bench,engine")
    ap.add_argument("--cpu", action="store_true",
                    help="pin jax to the CPU platform (validate the stage "
                         "logic with ZERO tunnel contact; the env var alone "
                         "is NOT enough — the image re-asserts axon)")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
        os.environ["JAX_PLATFORMS"] = "cpu"
    stages = args.stages.split(",")
    log(f"=== oneshot start (pid {os.getpid()}, stages {stages}) ===")
    try:
        if "init" in stages and not stage_init():
            sys.exit(3)
        if "kernels" in stages and not stage_kernels():
            log("=== aborted: xla kernel equality failed ===")
            sys.exit(4)
        pallas_ok = stage_pallas() if "pallas" in stages else False
        if "bench" in stages:
            stage_bench(pallas_ok)
        if "engine" in stages:
            stage_engine()
        if "scale" in stages:
            stage_scale()
    except Exception as e:  # noqa: BLE001 - log whatever stage died
        log(f"FATAL {type(e).__name__}: {str(e)[:300]}")
        for ln in traceback.format_exc().splitlines()[-10:]:
            log(f"  tb: {ln}")
        sys.exit(1)
    log("=== oneshot done ===")


if __name__ == "__main__":
    main()
