"""Span/hop-name cross-check pass (ISSUE 16 satellite).

Every LITERAL span/hop name opened at a tracer call site — the stage
and request tracers' ``<tracer>.span("name", ...)``
(runtime/tracing.py), the job tracer's ``JOB_TRACER.hop/note("name",
...)`` (runtime/job_trace.py), and the offload service's job-span
recorder ``self._trace(job, "name", ...)`` — must be DOCUMENTED in
README.md's '### Span-name table', and every table row must still have
a matching call site (both directions — the same discipline the event
and metric tables get). Unlike the events pass, DYNAMIC names are
legitimate here (``f"client.{op}"``, ``f"rpc.{code}"``, the job
tracer's ``f"{kind}.nested"`` degradation hop): the span vocabulary is
intentionally parameterized by op/code, so non-literal call sites are
simply exempt from the table check, never flagged.
"""

import re

from . import Finding, Repo, register

# literal-name span/hop call sites; group(1) = the name. Three shapes:
#   <tracer>.span("name"          stage + request tracers
#   <tracer>.hop("name" / .note("name"    the job tracer
#   self._trace(job, "name"       the offload service's job recorder
_SPAN_RE = re.compile(r"\.(?:span|hop|note)\(\s*\"([^\"]+)\"")
_SVC_RE = re.compile(r"\b_trace\(\s*\w+\s*,\s*\"([^\"]+)\"")


def source_span_names(repo: Repo) -> set:
    names = set()
    for sf in repo.package_files():
        names.update(_SPAN_RE.findall(sf.text))
        names.update(_SVC_RE.findall(sf.text))
    return names


def readme_span_rows(repo: Repo) -> list:
    """Span names from README's '### Span-name table': every backticked
    token in each row's first cell, '/'-alternations split (rows group
    related names, e.g. the learn hops)."""
    rows = []
    for cells in repo.readme_table_rows("Span-name table"):
        for span in re.findall(r"`([^`]+)`", cells[0]):
            for variant in span.split("/"):
                variant = variant.strip()
                if variant:
                    rows.append(variant)
    return rows


@register("span_names")
def run(repo: Repo = None) -> list:
    repo = repo or Repo()
    src = source_span_names(repo)
    rows = readme_span_rows(repo)
    out = []
    if src and not rows:
        return [Finding(
            "span_names", "", 0,
            "README.md has no '### Span-name table' section (or it is "
            "empty) — every literal tracer span/hop name must be "
            "documented there", key="no-table")]
    documented = set(rows)
    for name in sorted(src):
        if name not in documented:
            out.append(Finding(
                "span_names", "", 0,
                f"span/hop {name!r} is opened in source but missing "
                f"from README.md's Span-name table", key=f"undoc:{name}"))
    for name in sorted(documented):
        if name not in src:
            out.append(Finding(
                "span_names", "", 0,
                f"README Span-name table row {name!r} has no matching "
                f"tracer call site in source — delete the row or "
                f"restore the span", key=f"stale-row:{name}"))
    return out
