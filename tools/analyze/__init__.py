"""Static-analysis framework: one AST walk, many passes (ISSUE 9).

PRs 1-8 grew three ad-hoc repo lints (fail points, metric names, remote
commands), each with its own file scan, README parser and test wiring —
and the concurrency they guard grew much faster than the lints did.
This package unifies them behind one registry and adds the concurrency
passes the review rounds kept doing by hand:

  fail_points       test-armed fail points exist; source hooks documented
  metric_names      counter registrations <-> README metric table
  remote_commands   command registrations <-> README command table
  events            events.emit() names <-> README event table (and the
                    names must be plain string literals)
  span_names        tracer span/hop names <-> README span-name table
                    (literal call sites only; dynamic names are exempt)
  lock_discipline   `#: guarded_by` fields only touched under their lock
  thread_lifecycle  raw Thread/ThreadPoolExecutor spawns must route
                    through runtime/tasking's tracked helpers
  env_knobs         every PEGASUS_* env read <-> README knob table

Run everything:  python -m tools.analyze  (exit 0 = clean; --json for
machine-readable findings). Individual passes: --pass NAME (repeat).
Per-pass baselines (tools/analyze/baseline.json) grandfather known
findings by stable key so new regressions fail while tracked debt does
not; a stale baseline entry (fixed finding still listed) also fails —
the baseline must shrink, never rot.

The annotation grammar the concurrency passes consume is documented in
README.md's "Static analysis" section and in the pass modules.
"""

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.json"

# `#: <kind> <arg>` — the shared annotation grammar (lock_discipline,
# thread_lifecycle, env_knobs). Kind is one word; arg runs to end of line.
_ANNOT_RE = re.compile(r"#:\s*(guarded_by|requires|unguarded_ok|"
                       r"untracked_ok|env_knob)\b\s*(.*?)\s*$")


@dataclass
class Finding:
    """One pass finding. `key` is the stable baseline identity — never
    line-number-based (lines drift), always pass:file:symbol-ish."""

    pass_name: str
    file: str        # repo-relative path ('' for repo-level findings)
    line: int
    message: str
    key: str

    def as_dict(self) -> dict:
        return {"pass": self.pass_name, "file": self.file,
                "line": self.line, "message": self.message,
                "key": self.key}

    def render(self) -> str:
        loc = f"{self.file}:{self.line}: " if self.file else ""
        return f"[{self.pass_name}] {loc}{self.message}"


class SourceFile:
    """One parsed source file, shared across passes: text, line table,
    AST, and the `#:` annotations by line."""

    def __init__(self, path: Path, root: Path):
        self.path = path
        self.rel = str(path.relative_to(root))
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self._tree = None
        self.annotations = {}  # line(1-based) -> list[(kind, arg)]
        for i, line in enumerate(self.lines, 1):
            m = _ANNOT_RE.search(line)
            if m:
                self.annotations.setdefault(i, []).append(
                    (m.group(1), m.group(2)))

    @property
    def tree(self) -> ast.Module:
        if self._tree is None:
            self._tree = ast.parse(self.text, filename=self.rel)
        return self._tree

    def annotation(self, line: int, kind: str):
        """First annotation of `kind` on `line`, or None -> arg string."""
        for k, arg in self.annotations.get(line, []):
            if k == kind:
                return arg
        return None


class Repo:
    """The analysis target: a directory shaped like this repository.
    Tests build throwaway ones (tmp dir + synthetic modules + a tiny
    README) and run any pass against them."""

    def __init__(self, root=REPO):
        self.root = Path(root)
        self._files = {}

    def file(self, rel: str) -> SourceFile:
        sf = self._files.get(rel)
        if sf is None:
            sf = self._files[rel] = SourceFile(self.root / rel, self.root)
        return sf

    def _glob(self, patterns) -> list:
        out = []
        for pat in patterns:
            for p in sorted(self.root.glob(pat)):
                if p.is_file() and "__pycache__" not in p.parts:
                    out.append(self.file(str(p.relative_to(self.root))))
        return out

    def package_files(self) -> list:
        """The runtime package + the bench entry (what the original
        lints scanned)."""
        return self._glob(["pegasus_tpu/**/*.py", "bench.py"])

    def tool_files(self) -> list:
        return self._glob(["tools/*.py"])

    def test_files(self) -> list:
        return self._glob(["tests/**/*.py"])

    @property
    def readme(self) -> str:
        p = self.root / "README.md"
        return p.read_text() if p.exists() else ""

    def readme_section(self, heading: str) -> str:
        """Body of a `### heading` (or `## heading`) section up to the
        next same-or-higher heading — the ONE README slicer every
        table-driven pass shares."""
        level = "###" if not heading.startswith("## ") else "##"
        name = heading.removeprefix("## ")
        m = re.search(rf"^{level} {re.escape(name)}$(.*?)(?=^#{{2,3}} |\Z)",
                      self.readme, re.MULTILINE | re.DOTALL)
        return m.group(1) if m else ""

    def readme_table_rows(self, heading: str) -> list:
        """Markdown-table rows of a section: list of cell lists (outer
        pipes stripped, separator/header-rule rows dropped). The shared
        parser behind the metric/command/knob tables."""
        rows = []
        for line in self.readme_section(heading).splitlines():
            if not line.startswith("|"):
                continue
            # split on UNESCAPED pipes only: usage/alternation cells
            # legitimately contain `\|`
            cells = [c.strip() for c in
                     re.split(r"(?<!\\)\|", line.strip().strip("|"))]
            if cells and not all(set(c) <= {"-", " ", ":"} for c in cells):
                rows.append(cells)
        return rows


# ---------------------------------------------------------------- registry

_PASSES = {}


def register(name: str):
    """Decorator: register `fn(repo) -> list[Finding]` as a pass."""
    def deco(fn):
        _PASSES[name] = fn
        return fn
    return deco


def pass_names() -> list:
    _load_passes()
    return sorted(_PASSES)


def _load_passes() -> None:
    from . import (env_knobs, events, fail_points,  # noqa: F401
                   lock_discipline, metric_names, remote_commands,
                   span_names, thread_lifecycle)


def run_pass(name: str, repo: Repo = None) -> list:
    _load_passes()
    return _PASSES[name](repo or Repo())


def load_baseline(path=BASELINE_PATH) -> dict:
    """{pass_name: set(keys)} of grandfathered findings."""
    p = Path(path)
    if not p.exists():
        return {}
    data = json.loads(p.read_text())
    return {k: set(v) for k, v in data.items()}


@dataclass
class Report:
    findings: list = field(default_factory=list)     # new (failing)
    grandfathered: list = field(default_factory=list)
    stale_baseline: list = field(default_factory=list)  # (pass, key)
    ran: list = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings and not self.stale_baseline

    def as_dict(self) -> dict:
        return {
            "clean": self.clean,
            "passes": self.ran,
            "findings": [f.as_dict() for f in self.findings],
            "grandfathered": [f.as_dict() for f in self.grandfathered],
            "stale_baseline": [{"pass": p, "key": k}
                               for p, k in self.stale_baseline],
        }


def run_all(repo: Repo = None, passes=None, baseline=None) -> Report:
    """Run the registered passes against `repo`, splitting findings by
    the baseline. A baseline key with no live finding is STALE and fails
    the run (debt must be re-justified or deleted, never forgotten)."""
    repo = repo or Repo()
    baseline = load_baseline() if baseline is None else baseline
    _load_passes()
    names = passes or sorted(_PASSES)
    report = Report(ran=list(names))
    for name in names:
        allowed = baseline.get(name, set())
        seen = set()
        for f in _PASSES[name](repo):
            if f.key in allowed:
                report.grandfathered.append(f)
                seen.add(f.key)
            else:
                report.findings.append(f)
        for key in sorted(allowed - seen):
            report.stale_baseline.append((name, key))
    return report
