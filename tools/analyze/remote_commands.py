"""Remote-command cross-check pass (migrated from
tools/check_remote_commands.py; that file remains as a thin CLI shim).

Every remote command registered in source (``commands.register("name")``
on a RemoteCommandService, or ``self.register("name")`` inside
runtime/remote_command.py's register_defaults) must be DOCUMENTED in
README.md's '### Remote-command table', and every table row must still
name a registered command (both directions).
"""

import re

from . import Finding, Repo, register

# Deliberately NOT a bare `.register(` — RpcServer task-code
# registrations share that shape.
_CMDS_RE = re.compile(r"\bcommands\.register\(\s*\"([^\"]+)\"")
_SELF_RE = re.compile(r"\bself\.register\(\s*\"([^\"]+)\"")


def source_commands(repo: Repo) -> set:
    names = set()
    for sf in repo.package_files():
        names.update(_CMDS_RE.findall(sf.text))
        if sf.path.name == "remote_command.py":
            names.update(_SELF_RE.findall(sf.text))
    return names


def readme_command_rows(repo: Repo) -> list:
    """Command names from README's '### Remote-command table' section:
    each row's first backticked token (the rest of the span is usage —
    usage strings legitimately contain escaped `\\|` alternations, which
    the shared cell splitter already treats as cell text)."""
    rows = []
    for cells in repo.readme_table_rows("Remote-command table"):
        first = re.search(r"`([^`\s]+)", cells[0])
        if first:
            rows.append(first.group(1))
    return rows


def lint_findings(src: set, rows: list) -> list:
    """Parameterized core shared with the CLI shim."""
    if not rows:
        return [Finding(
            "remote_commands", "", 0,
            "README.md has no '### Remote-command table' section "
            "(or it is empty) — every registered remote command must "
            "be documented there", key="no-table")]
    out = []
    documented = set(rows)
    for name in sorted(src):
        if name not in documented:
            out.append(Finding(
                "remote_commands", "", 0,
                f"remote command {name!r} is registered in source but "
                f"missing from README.md's Remote-command table",
                key=f"undoc:{name}"))
    for name in sorted(documented):
        if name not in src:
            out.append(Finding(
                "remote_commands", "", 0,
                f"README Remote-command table row {name!r} has no matching "
                f"registration in source — delete the row or restore the "
                f"command", key=f"stale-row:{name}"))
    return out


@register("remote_commands")
def run(repo: Repo = None) -> list:
    repo = repo or Repo()
    return lint_findings(source_commands(repo), readme_command_rows(repo))
