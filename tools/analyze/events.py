"""Structured-event cross-check pass (ISSUE 12 satellite).

Every event name emitted in source (``events.emit("name", ...)`` — the
flight recorder's bus, runtime/events.py) must be DOCUMENTED in
README.md's '### Event table', and every table row must still have a
matching emit site (both directions — the same discipline the
metric-name and remote-command tables already get). Additionally, the
first argument of every ``events.emit(`` call must be a PLAIN string
literal: the event-name vocabulary is the flight recorder's
first-cause/filter surface, and a dynamic name is invisible both to
this lint and to anyone grepping an incident artifact.
"""

import re

from . import Finding, Repo, register

# a literal-name emit; group(1) = the name
_EMIT_RE = re.compile(r"\bevents\.emit\(\s*\"([^\"]+)\"")
# any emit call, for spotting the non-literal ones (f-strings count as
# non-literal: a hole makes the name dynamic)
_ANY_EMIT_RE = re.compile(r"\bevents\.emit\(\s*([^)\n]{0,60})")


def source_event_names(repo: Repo) -> set:
    names = set()
    for sf in repo.package_files():
        names.update(_EMIT_RE.findall(sf.text))
    return names


def nonliteral_emits(repo: Repo) -> list:
    """[(file, line, argument-snippet)] for emit calls whose first
    argument is not a plain string literal."""
    out = []
    for sf in repo.package_files():
        for m in _ANY_EMIT_RE.finditer(sf.text):
            if _EMIT_RE.match(sf.text, m.start()):
                continue
            line = sf.text.count("\n", 0, m.start()) + 1
            out.append((sf, line, m.group(1).strip()))
    return out


def readme_event_rows(repo: Repo) -> list:
    """Event names from README's '### Event table': each row's first
    backticked token."""
    rows = []
    for cells in repo.readme_table_rows("Event table"):
        first = re.search(r"`([^`\s]+)", cells[0])
        if first:
            rows.append(first.group(1))
    return rows


@register("events")
def run(repo: Repo = None) -> list:
    repo = repo or Repo()
    src = source_event_names(repo)
    rows = readme_event_rows(repo)
    out = []
    if src and not rows:
        return [Finding(
            "events", "", 0,
            "README.md has no '### Event table' section (or it is "
            "empty) — every events.emit() name must be documented there",
            key="no-table")]
    documented = set(rows)
    for name in sorted(src):
        if name not in documented:
            out.append(Finding(
                "events", "", 0,
                f"event {name!r} is emitted in source but missing from "
                f"README.md's Event table", key=f"undoc:{name}"))
    for name in sorted(documented):
        if name not in src:
            out.append(Finding(
                "events", "", 0,
                f"README Event table row {name!r} has no matching "
                f"events.emit() in source — delete the row or restore "
                f"the emit", key=f"stale-row:{name}"))
    for sf, line, snippet in nonliteral_emits(repo):
        out.append(Finding(
            "events", sf.rel, line,
            f"events.emit() name must be a plain string literal "
            f"(got: {snippet!r}) — dynamic names are invisible to this "
            f"lint and to incident-artifact greps",
            key=f"nonliteral:{sf.rel}:{snippet[:40]}"))
    return out
