"""Env-knob registry pass: every PEGASUS_* read <-> README knob table.

Before this pass the repo read ~67 ``PEGASUS_*`` environment knobs and
documented roughly 28 of them, scattered through prose — an operator
could not enumerate the configuration surface, and a renamed knob left
its documentation silently lying. Now README.md carries a
'### Configuration-knob table' (name | default | effect) and this pass
enforces BOTH directions:

  * every knob the code READS must have a table row;
  * every table row must still be read somewhere (a deleted knob's row
    documents configuration that does nothing — worse than nothing).

What counts as a read (AST, not grep — a knob mentioned in a docstring
is documentation, not configuration surface):

  * ``os.environ.get("PEGASUS_X")`` / ``os.getenv`` / ``environ[...]``
    (Load context only — writes into a child process's env dict are not
    reads) / ``environ.setdefault``;
  * the same with the name behind a module-level constant
    (``_DEPTH_ENV = "PEGASUS_COMPACT_PIPELINE_DEPTH"``);
  * helper wrappers whose name starts with ``_env``
    (lane_guard's ``_env_float``/``_env_int``);
  * prefix families: an env-read of ``f"{env_prefix}_DEADLINE_S"``
    registers the template ``*_DEADLINE_S``; literal ``PEGASUS_*``
    prefixes flowing into an ``env_prefix`` parameter (as its default,
    or as the first argument of a ``*.from_env(...)`` call) expand every
    template — lane_guard's two lanes times four knobs resolve to all
    eight real names;
  * ``#: env_knob NAME [NAME...]`` declares knobs the walker cannot see
    (none today; the escape hatch for future dynamic composition).

Scanned: pegasus_tpu/, tools/*.py, bench.py, tests/conftest.py (the
test harness reads real knobs like PEGASUS_TEST_TPU).
"""

import ast
import re

from . import Finding, Repo, register

_ENV_CALL_ATTRS = {"get", "getenv", "setdefault"}


def _is_environ(node) -> bool:
    """`os.environ` / `environ` / `os` (for os.getenv)."""
    s = ""
    try:
        s = ast.unparse(node)
    except Exception:  # noqa: BLE001
        return False
    return s in ("os.environ", "environ", "os")


def _const_str(node, consts) -> str:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id, "")
    return ""


def _fstring_template(node) -> str:
    """JoinedStr with a leading hole and literal tail -> '*<tail>'."""
    if not isinstance(node, ast.JoinedStr) or len(node.values) < 2:
        return ""
    if not isinstance(node.values[0], ast.FormattedValue):
        return ""
    tail = ""
    for v in node.values[1:]:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            tail += v.value
        else:
            return ""
    return "*" + tail if tail else ""


def _collect_file(sf, knobs: set, templates: set, prefixes: set) -> None:
    # module-level string constants (name indirection)
    consts = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    consts[t.id] = node.value.value

    def add_name_arg(arg) -> None:
        s = _const_str(arg, consts)
        if s.startswith("PEGASUS_"):
            knobs.add(s)
        else:
            t = _fstring_template(arg)
            if t:
                templates.add(t)

    for node in ast.walk(sf.tree):
        # environ["X"] in Load context
        if isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and _is_environ(node.value):
            add_name_arg(node.slice)
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute):
            # os.environ.get / os.getenv / environ.setdefault
            if fn.attr in _ENV_CALL_ATTRS and _is_environ(fn.value) \
                    and node.args:
                add_name_arg(node.args[0])
            # prefix families: SomeConfig.from_env("PEGASUS_READ_LANE",…)
            if fn.attr == "from_env" and node.args:
                s = _const_str(node.args[0], consts)
                if s.startswith("PEGASUS_"):
                    prefixes.add(s)
        elif isinstance(fn, ast.Name):
            # helper wrappers: _env_float(f"{env_prefix}_DEADLINE_S", …)
            if fn.id.startswith("_env") and node.args:
                add_name_arg(node.args[0])
    # env-prefix parameter DEFAULTS count as family prefixes too
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            defaults = args.defaults
            params = args.args[len(args.args) - len(defaults):]
            for p, d in zip(params, defaults):
                if p.arg.endswith("prefix") and \
                        isinstance(d, ast.Constant) and \
                        isinstance(d.value, str) and \
                        d.value.startswith("PEGASUS_"):
                    prefixes.add(d.value)
    # declared knobs the walker cannot see
    for anns in sf.annotations.values():
        for kind, arg in anns:
            if kind == "env_knob":
                for name in arg.split():
                    if name.startswith("PEGASUS_"):
                        knobs.add(name)


def source_knobs(repo: Repo) -> set:
    """Every PEGASUS_* env name the code reads (families expanded)."""
    knobs, templates, prefixes = set(), set(), set()
    files = repo.package_files() + repo.tool_files()
    conftest = repo.root / "tests" / "conftest.py"
    if conftest.exists():
        files.append(repo.file("tests/conftest.py"))
    for sf in files:
        if "PEGASUS_" not in sf.text and "environ" not in sf.text:
            continue
        _collect_file(sf, knobs, templates, prefixes)
    for t in templates:
        for p in prefixes:
            knobs.add(p + t[1:])
    return knobs


_ROW_NAME_RE = re.compile(r"`(PEGASUS_[A-Z0-9_]+)`")


def readme_knob_rows(repo: Repo) -> list:
    """Knob names from README's '### Configuration-knob table'."""
    rows = []
    for cells in repo.readme_table_rows("Configuration-knob table"):
        m = _ROW_NAME_RE.search(cells[0])
        if m:
            rows.append(m.group(1))
    return rows


def lint_findings(src: set, rows: list) -> list:
    out = []
    if not rows:
        return [Finding(
            "env_knobs", "", 0,
            "README.md has no '### Configuration-knob table' section "
            "(or it is empty) — every PEGASUS_* knob the code reads "
            "must be documented there", key="no-table")]
    documented = set(rows)
    for name in sorted(src - documented):
        out.append(Finding(
            "env_knobs", "", 0,
            f"env knob {name} is read in source but missing from "
            f"README.md's Configuration-knob table",
            key=f"undoc:{name}"))
    for name in sorted(documented - src):
        out.append(Finding(
            "env_knobs", "", 0,
            f"README Configuration-knob table row {name} is read "
            f"nowhere in source — delete the row or restore the knob",
            key=f"stale-row:{name}"))
    return out


@register("env_knobs")
def run(repo: Repo = None) -> list:
    repo = repo or Repo()
    return lint_findings(source_knobs(repo), readme_knob_rows(repo))
