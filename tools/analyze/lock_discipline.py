"""Lock-discipline pass: `#: guarded_by` fields must only be touched
under their lock.

The static half of the concurrency lint plane (runtime/lockrank.py is
the runtime half): mutable shared state is ANNOTATED with the lock that
guards it, and this pass AST-checks every access. The annotation
grammar (also in README.md's "Static analysis" section):

  self._l0 = []            #: guarded_by self._lock
      Declares an instance attribute guarded by a lock expression
      (usually another attribute of the same object). The comment rides
      the declaring assignment's line, or the line directly above it.
      Module-level names work the same way::

          _POOL = None     #: guarded_by _POOL_LOCK

  def _alloc_file_locked(self):  #: requires self._lock
      Declares a method (or module function) that is only ever called
      with the lock already held — its guarded accesses are trusted, not
      flagged. The annotation is an ASSUMPTION about callers (v1 does
      not verify call sites); name such methods `*_locked` by
      convention so reviewers see the contract at the call site too.

  d = self._last_committed_decree + 1  #: unguarded_ok racy-read: ...
      Suppresses findings on one line, with a MANDATORY reason — a
      deliberate lock-free read (monotonic hint, gauge snapshot) is
      fine, an undocumented one is a finding. On a `def` line the
      escape covers the whole method (single-threaded recovery helpers
      called only from __init__).

Checking rules:
  * `with <lockexpr>:` opens a guarded scope for that expression (all
    context items of the with count; `with a, b:` holds both).
  * a Condition constructed over a lock aliases it:
    `self._cv = threading.Condition(self._lock)` (or
    `lockrank.named_condition(name, self._lock)`) means holding
    `self._cv` implies holding `self._lock`.
  * `__init__` is exempt (construction happens-before publication).
  * nested functions/lambdas do NOT inherit the enclosing `with` scope:
    a closure handed to a pool runs on another thread after the lock is
    long gone — its guarded accesses must re-acquire or be escaped.
  * only `self.<attr>` accesses are checked against instance guards
    (cross-object accesses are out of scope for v1), plus bare-name
    accesses for module-level guards.
"""

import ast

from . import Finding, Repo, register


def _unparse(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 - any unparse failure = no match
        return ""


def _target_attr(node):
    """'self.X' assignment target -> X, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _annotation_for(sf, line: int, kind: str):
    """Annotation of `kind` bound to `line`: same line, or a STANDALONE
    comment line directly above (long declarations put the comment on
    its own line — a trailing comment on the previous statement binds to
    THAT statement, never leaks downward)."""
    arg = sf.annotation(line, kind)
    if arg is None and line >= 2 \
            and sf.lines[line - 2].lstrip().startswith("#"):
        arg = sf.annotation(line - 1, kind)
    return arg


def _cond_alias(value):
    """If `value` constructs a Condition over a lock expression, return
    that lock expression string, else None. Recognizes
    threading.Condition(lock) / Condition(lock) /
    lockrank.named_condition(name, lock) / named_condition(name, lock)."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    name = fn.attr if isinstance(fn, ast.Attribute) else \
        fn.id if isinstance(fn, ast.Name) else ""
    if name == "Condition" and value.args:
        return _unparse(value.args[0])
    if name == "named_condition":
        if len(value.args) >= 2:
            return _unparse(value.args[1])
        for kw in value.keywords:
            if kw.arg == "lock":
                return _unparse(kw.value)
    return None


class _ClassGuards:
    """Per-class guard declarations harvested from annotated
    assignments anywhere in the class body (usually __init__)."""

    def __init__(self):
        self.fields = {}   # attr -> lock expr string
        self.aliases = {}  # cond attr expr ("self._cv") -> lock expr

    def implied(self, held: set) -> set:
        """Close the held-set over condition aliases."""
        out = set(held)
        for cv, lk in self.aliases.items():
            if cv in out:
                out.add(lk)
        return out


def _harvest_class(sf, cls: ast.ClassDef) -> _ClassGuards:
    g = _ClassGuards()
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                attr = _target_attr(t)
                if attr is None:
                    continue
                lock = _annotation_for(sf, node.lineno, "guarded_by")
                if lock:
                    g.fields[attr] = lock
                alias = _cond_alias(node.value) if node.value else None
                if alias:
                    g.aliases[f"self.{attr}"] = alias
    return g


class _MethodChecker(ast.NodeVisitor):
    """Walk one function body tracking the set of held lock expressions;
    flag guarded accesses made without the guard held."""

    def __init__(self, sf, guards, held, findings, scope_name,
                 module_guards=None):
        self.sf = sf
        self.guards = guards          # _ClassGuards or None (module fn)
        self.module_guards = module_guards or {}
        self.held = set(held)
        self.findings = findings
        self.scope = scope_name

    # ------------------------------------------------------------- scopes

    def visit_With(self, node: ast.With):
        added = []
        for item in node.items:
            expr = _unparse(item.context_expr)
            if expr:
                added.append(expr)
        saved = self.held
        self.held = self.held | set(added)
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    def _nested(self, body):
        # a closure runs whenever its caller decides — usually another
        # thread; it inherits NOTHING
        checker = _MethodChecker(self.sf, self.guards, set(),
                                 self.findings, self.scope,
                                 self.module_guards)
        for stmt in body:
            checker.visit(stmt)

    def visit_FunctionDef(self, node):
        self._nested(node.body)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._nested([node.body])

    # ------------------------------------------------------------ accesses

    def _flag(self, node, name: str, lock: str):
        reason = self.sf.annotation(node.lineno, "unguarded_ok")
        if reason is not None and reason.strip():
            return  # documented escape; an EMPTY reason does not count
        self.findings.append(Finding(
            "lock_discipline", self.sf.rel, node.lineno,
            f"{self.scope}: access to {name} (guarded by {lock}) "
            f"outside `with {lock}` — wrap it, annotate the method "
            f"`#: requires {lock}`, or escape the line with "
            f"`#: unguarded_ok <reason>`",
            key=f"{self.sf.rel}:{self.scope}:{name}"))

    def visit_Attribute(self, node: ast.Attribute):
        attr = _target_attr(node)
        if attr is not None and self.guards is not None \
                and attr in self.guards.fields:
            lock = self.guards.fields[attr]
            if lock not in self.guards.implied(self.held):
                self._flag(node, f"self.{attr}", lock)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name):
        lock = self.module_guards.get(node.id)
        if lock is not None and lock not in self.held:
            self._flag(node, node.id, lock)
        self.generic_visit(node)


def _check_function(sf, fn, guards, module_guards, findings,
                    scope: str) -> None:
    if fn.name == "__init__":
        return
    method_escape = _annotation_for(sf, fn.lineno, "unguarded_ok")
    if method_escape is not None and method_escape.strip():
        return
    held = set()
    required = _annotation_for(sf, fn.lineno, "requires")
    if required:
        held.update(r.strip() for r in required.split(",") if r.strip())
    checker = _MethodChecker(sf, guards, held, findings, scope,
                             module_guards)
    for stmt in fn.body:
        checker.visit(stmt)


def check_file(sf, findings: list) -> None:
    # module-level guards: `_POOL = None  #: guarded_by _POOL_LOCK`
    module_guards = {}
    for node in sf.tree.body:
        if isinstance(node, ast.Assign):
            lock = _annotation_for(sf, node.lineno, "guarded_by")
            if lock:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        module_guards[t.id] = lock
    for node in sf.tree.body:
        if isinstance(node, ast.ClassDef):
            guards = _harvest_class(sf, node)
            # nested-class guard declarations also register (one level)
            for fn in node.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    _check_function(sf, fn, guards, module_guards,
                                    findings, f"{node.name}.{fn.name}")
                elif isinstance(fn, ast.ClassDef):
                    inner = _harvest_class(sf, fn)
                    for ifn in fn.body:
                        if isinstance(ifn, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            _check_function(
                                sf, ifn, inner, module_guards, findings,
                                f"{node.name}.{fn.name}.{ifn.name}")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _check_function(sf, node, None, module_guards, findings,
                            node.name)


@register("lock_discipline")
def run(repo: Repo = None) -> list:
    repo = repo or Repo()
    findings = []
    for sf in repo.package_files():
        if "guarded_by" in sf.text or "#: requires" in sf.text:
            check_file(sf, findings)
    return findings
