"""`python -m tools.analyze` — run the full static-analysis plane.

Exit 0: every pass clean (modulo the committed baseline, which must
itself stay exact — a stale entry fails). Human-readable findings on
stderr; `--json` prints the full machine-readable report on stdout.

Options:
  --json            machine-readable report to stdout
  --pass NAME       run only NAME (repeatable; default: all passes)
  --root PATH       analyze a different repo root (tests)
  --no-baseline     ignore the committed baseline (show ALL findings)
  --list            list registered passes and exit
"""

import argparse
import json
import sys

from . import BASELINE_PATH, Repo, load_baseline, pass_names, run_all


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.analyze")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--pass", dest="passes", action="append")
    ap.add_argument("--root", default=None)
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args(argv)
    if args.list:
        print("\n".join(pass_names()))
        return 0
    repo = Repo(args.root) if args.root else Repo()
    baseline = {} if args.no_baseline else load_baseline()
    report = run_all(repo, passes=args.passes, baseline=baseline)
    if args.json:
        print(json.dumps(report.as_dict(), indent=2))
    for f in report.findings:
        print(f"analyze: {f.render()}", file=sys.stderr)
    for pass_name, key in report.stale_baseline:
        print(f"analyze: [{pass_name}] STALE baseline entry {key!r} — "
              f"the finding is gone; delete it from {BASELINE_PATH.name}",
              file=sys.stderr)
    if not args.json:
        n_gf = len(report.grandfathered)
        status = "OK" if report.clean else "FAIL"
        print(f"analyze: {status} — {len(report.ran)} passes, "
              f"{len(report.findings)} findings"
              + (f", {n_gf} grandfathered" if n_gf else ""))
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
