"""Thread/executor-lifecycle pass: no raw spawns outside the tracked
helpers.

The PR 5 rc=134 shutdown abort was EXACTLY this bug class: daemon
threads nobody registered anywhere died inside XLA dispatches at
interpreter finalization, and nothing could have joined them because
nothing knew they existed. runtime/tasking.py now provides tracked
spawn helpers — ``spawn_thread(...)`` and ``tracked_executor(...)`` —
that register every thread/pool in a process-wide registry with a
bounded ``TRACKED.join_all()`` teardown (tests/conftest.py calls it at
session finish). This pass makes the discipline machine-checked:

  * every direct ``Thread(...)`` / ``threading.Thread(...)`` /
    ``ThreadPoolExecutor(...)`` / ``concurrent.futures.
    ThreadPoolExecutor(...)`` CALL outside runtime/tasking.py is a
    finding;
  * so is defining a ``threading.Thread`` SUBCLASS (a spawn factory in
    disguise) — lane_guard's deliberately-abandoned deadline workers
    carry the escape hatch;
  * the escape is ``#: untracked_ok <reason>`` on the call (or class)
    line, reason mandatory: a thread the registry cannot see must say
    why its lifecycle is safe.
"""

import ast

from . import Finding, Repo, register

# the helper module itself (and only it) may touch the raw primitives
_HELPER_FILES = {"pegasus_tpu/runtime/tasking.py"}

_SPAWN_CALLEES = {
    "Thread", "threading.Thread",
    "ThreadPoolExecutor", "futures.ThreadPoolExecutor",
    "concurrent.futures.ThreadPoolExecutor",
}


def _callee(node: ast.Call) -> str:
    try:
        return ast.unparse(node.func)
    except Exception:  # noqa: BLE001 - no name = no match
        return ""


def _thread_base(base) -> bool:
    try:
        return ast.unparse(base) in ("Thread", "threading.Thread")
    except Exception:  # noqa: BLE001
        return False


def check_file(sf, findings: list) -> None:
    scope = [sf.path.stem]

    def visit(node):
        name = getattr(node, "name", None)
        pushed = False
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)) and name:
            scope.append(name)
            pushed = True
        if isinstance(node, ast.ClassDef) and \
                any(_thread_base(b) for b in node.bases):
            reason = sf.annotation(node.lineno, "untracked_ok")
            if reason is None or not reason.strip():
                findings.append(Finding(
                    "thread_lifecycle", sf.rel, node.lineno,
                    f"class {node.name} subclasses threading.Thread — a "
                    f"spawn factory the tracked registry cannot see; "
                    f"route instances through runtime/tasking.spawn_thread "
                    f"or escape the class line with "
                    f"`#: untracked_ok <reason>`",
                    key=f"{sf.rel}:class:{node.name}"))
        if isinstance(node, ast.Call) and _callee(node) in _SPAWN_CALLEES:
            reason = sf.annotation(node.lineno, "untracked_ok")
            if reason is None or not reason.strip():
                where = ".".join(scope[1:]) or "<module>"
                findings.append(Finding(
                    "thread_lifecycle", sf.rel, node.lineno,
                    f"raw {_callee(node)}(...) in {where} — use "
                    f"runtime/tasking.spawn_thread / tracked_executor "
                    f"(registers join/shutdown) or escape the line with "
                    f"`#: untracked_ok <reason>`",
                    key=f"{sf.rel}:{where}:{_callee(node)}"))
        for child in ast.iter_child_nodes(node):
            visit(child)
        if pushed:
            scope.pop()

    visit(sf.tree)


@register("thread_lifecycle")
def run(repo: Repo = None) -> list:
    repo = repo or Repo()
    findings = []
    for sf in repo.package_files():
        if sf.rel in _HELPER_FILES:
            continue
        if "Thread" in sf.text:  # cheap pre-filter
            check_file(sf, findings)
    return findings
