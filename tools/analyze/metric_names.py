"""Metric-name cross-check pass (migrated from
tools/check_metric_names.py; that file remains as a thin CLI shim).

Every perf-counter name registered in source
(``counters.rate/percentile/number/volatile_number("name")``) must be
DOCUMENTED in README.md's Observability metric tables, and every row of
README's '### Metric-name table' must still have a matching counter
registration (both directions — see the shim's docstring for the full
rationale and the wildcard rules for dynamic names).
"""

import re

from . import Finding, Repo, register

# a counter registration call; the name argument is parsed from here on
_KIND_RE = re.compile(
    r"counters\.(?:rate|percentile|number|volatile_number)\(")
# <prefix-expr> +  (e.g. self._pfx + "put_qps") -> leading wildcard
_PFX_RE = re.compile(r"\s*[A-Za-z_][\w.]*\s*\+\s*")
# one (f-)string literal; `\s*` spans newlines, so adjacent literals in a
# multi-line implicit concatenation chain all parse
_STR_RE = re.compile(r"\s*(f?)\"([^\"]*)\"")
_JOIN_RE = re.compile(r"\s*\+\s*")


def _wildcard(is_fstring: str, name: str) -> str:
    if is_fstring:
        name = re.sub(r"\{[^}]*\}", "*", name)
    return name


def _name_at(text: str, pos: int) -> str:
    """Parse the counter-name expression starting at `pos` (just past the
    opening paren) into a wildcard pattern: f-string holes and non-literal
    sub-expressions become '*', adjacent/'+'-joined literals concatenate.
    Returns '' when the argument holds no string literal at all."""
    prefix = ""
    mp = _PFX_RE.match(text, pos)
    if mp:
        prefix, pos = "*", mp.end()
    parts = []
    while True:
        ms = _STR_RE.match(text, pos)
        if not ms:
            break
        parts.append(_wildcard(ms.group(1), ms.group(2)))
        pos = ms.end()
        mj = _JOIN_RE.match(text, pos)
        if mj:
            if _STR_RE.match(text, mj.end()):
                pos = mj.end()
            else:  # '+ expr' with a non-literal tail
                parts.append("*")
                break
    return prefix + "".join(parts) if parts else ""


def source_metric_names(repo: Repo) -> set:
    names = set()
    for sf in repo.package_files():
        for m in _KIND_RE.finditer(sf.text):
            name = _name_at(sf.text, m.end())
            if name:
                names.add(name)
    return names


def _probe(name: str) -> str:
    """Longest wildcard-free segment of the name (dots trimmed) — what
    must literally appear in the README's metric tables."""
    segments = [s.strip(".") for s in name.split("*")]
    segments = [s for s in segments if s]
    return max(segments, key=len, default="")


def readme_metric_rows(repo: Repo) -> list:
    """Counter-name variants from README's '### Metric-name table'
    section: one entry per backticked span in each row's first cell,
    split on ' / ' and '\\|' alternations, `<placeholder>` -> '*'."""
    rows = []
    for cells in repo.readme_table_rows("Metric-name table"):
        for span in re.findall(r"`([^`]+)`", cells[0]):
            for variant in re.split(r"\\\||/", span):
                variant = variant.strip()
                if variant:
                    rows.append(re.sub(r"<[^>]*>", "*", variant))
    return rows


def lint_findings(src: set, rows: list, readme: str) -> list:
    """Parameterized core shared with the CLI shim."""
    out = []
    for name in sorted(src):
        probe = _probe(name)
        if probe and probe not in readme:
            out.append(Finding(
                "metric_names", "", 0,
                f"source counter {name!r} is undocumented — add it to "
                f"README.md's Observability metric tables "
                f"(probe segment {probe!r} not found)",
                key=f"undoc:{name}"))
    # reverse pass: a README row must still name a registered counter.
    # A row may also be covered by a FULLY-dynamic registration of the
    # shape `f"{base}.count"` -> `*.count` (the tracing stage family):
    # only that narrow leading-wildcard + dot-suffix shape is accepted
    # as coverage — broader wildcards like `**_qps` would quietly cover
    # ANY `<ghost>_qps` row and gut the lint.
    haystack = "\n".join(sorted(src))
    suffixes = [s[1:] for s in src
                if re.fullmatch(r"\*(\.[A-Za-z0-9_]+)+", s)]
    for row in rows:
        probe = _probe(row)
        resolved = row.replace("*", "X")
        if probe and probe not in haystack \
                and not any(resolved.endswith(sfx) for sfx in suffixes):
            out.append(Finding(
                "metric_names", "", 0,
                f"README metric row {row!r} has no matching counter "
                f"registration in source (probe segment {probe!r}) — "
                f"delete the row or restore the counter",
                key=f"stale-row:{row}"))
    return out


@register("metric_names")
def run(repo: Repo = None) -> list:
    repo = repo or Repo()
    return lint_findings(source_metric_names(repo),
                         readme_metric_rows(repo), repo.readme)
