"""Fail-point cross-check pass (migrated from tools/check_fail_points.py;
that file remains as a thin CLI shim).

  1. every fail-point name ARMED in tests (``cfg("name", ...)``) must
     exist as a hook in source (``fail_point("name")`` / ``inject(...)``/
     ``_fail(...)`` / ``_inject(...)``) — a test arming a point that no
     code evaluates silently tests nothing;
  2. every fail-point hook in source must be DOCUMENTED in README.md
     (the Robustness section's fail-point table) — chaos hooks nobody can
     discover rot.

Dynamic names (``fail_point(f"rpc.{code}")``) become prefix wildcards
(``rpc.*``): a test may arm any name under the prefix, and the README
must mention the prefix.
"""

import re

from . import Finding, Repo, register

_CALL_RE = re.compile(
    r"\b(?:fail_point|_fail|inject|_inject|_stage_fail)\(\s*(f?)\"([^\"]+)\"")
_CFG_RE = re.compile(r"\bcfg\(\s*\"([^\"]+)\"")


def _points_in(files) -> set:
    names = set()
    for sf in files:
        for m in _CALL_RE.finditer(sf.text):
            name = m.group(2)
            if m.group(1):  # f-string: every {expr} hole becomes a wildcard
                name = re.sub(r"\{[^}]*\}", "*", name)
            names.add(name)
    return names


def source_points(repo: Repo) -> set:
    return _points_in(repo.package_files())


def test_local_points(repo: Repo) -> set:
    """Hooks evaluated INSIDE tests (the fail-point mini-language unit
    tests arm and evaluate throwaway names like 'p1' in the same file) —
    legitimate, but they need no README documentation."""
    return _points_in(repo.test_files())


def test_armed_points(repo: Repo) -> set:
    names = set()
    for sf in repo.test_files():
        names.update(_CFG_RE.findall(sf.text))
    return names


def _matches(name: str, source: set) -> bool:
    if name in source:
        return True
    return any(s.endswith("*") and name.startswith(s[:-1]) for s in source)


def lint_findings(src: set, armed: set, hooks: set, readme: str) -> list:
    """Parameterized core (the CLI shim feeds its own — possibly
    monkeypatched — collectors through here)."""
    out = []
    for name in sorted(armed):
        if not _matches(name, hooks):
            out.append(Finding(
                "fail_points", "", 0,
                f"tests arm fail point {name!r} but no source hook "
                f"evaluates it (known: {sorted(hooks)})",
                key=f"armed:{name}"))
    for name in sorted(src):
        probe = name.split("*")[0] if "*" in name else name
        if probe not in readme:
            out.append(Finding(
                "fail_points", "", 0,
                f"source fail point {name!r} is undocumented — add it to "
                f"README.md's Robustness fail-point table",
                key=f"undoc:{name}"))
    return out


@register("fail_points")
def run(repo: Repo = None) -> list:
    repo = repo or Repo()
    src = source_points(repo)
    armed = test_armed_points(repo)
    hooks = src | test_local_points(repo)
    return lint_findings(src, armed, hooks, repo.readme)
