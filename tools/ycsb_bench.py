"""YCSB workload-A style benchmark over a live cluster (BASELINE.json
config #3: 50/50 read/update, zipfian-ish keys, 32 hash partitions).

Boots an in-process onebox (1 meta + 3 replica nodes over real sockets)
unless --meta points at a running cluster, loads N records, then drives
50/50 read/update from T client threads and reports ops/sec + latency
percentiles as one JSON line.

    python tools/ycsb_bench.py [--records 10000] [--ops 20000] [--threads 4]
"""

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def zipf_key(rng, n, _cache={}):
    """Proper zipf(0.99) ranks via bench.ZipfKeys (the YCSB quick-zipfian
    generator) — the old continuous-inverse-transform approximation put
    ~91% of picks on key 0, which benchmarked a single hot key."""
    from bench import ZipfKeys

    z = _cache.get(n)
    if z is None:
        z = _cache[n] = ZipfKeys(n)
    return z.pick(rng)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--meta", default="", help="existing cluster (host:port)")
    ap.add_argument("--records", type=int, default=10_000)
    ap.add_argument("--ops", type=int, default=20_000)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--partitions", type=int, default=32)
    ap.add_argument("--value_size", type=int, default=100)
    ns = ap.parse_args()

    from pegasus_tpu.client import MetaResolver, PegasusClient

    from tools._onebox import resolve_cluster

    meta_addr, cluster = resolve_cluster(ns.meta, "ycsb", ns.partitions)
    try:

        value = os.urandom(ns.value_size)
        load_cli = PegasusClient(MetaResolver([meta_addr], "ycsb"))
        t0 = time.perf_counter()
        for i in range(ns.records):
            load_cli.set(b"user%012d" % i, b"f0", value)
        load_s = time.perf_counter() - t0
        load_cli.close()

        lat_us = []
        lat_lock = threading.Lock()
        errors = [0]

        def worker(tid):
            rng = random.Random(tid)
            cli = PegasusClient(MetaResolver([meta_addr], "ycsb"))
            local = []
            for _ in range(ns.ops // ns.threads):
                k = b"user%012d" % (zipf_key(rng, ns.records) % ns.records)
                s = time.perf_counter()
                try:
                    if rng.random() < 0.5:
                        cli.get(k, b"f0")
                    else:
                        cli.set(k, b"f0", value)
                except Exception:
                    errors[0] += 1
                local.append((time.perf_counter() - s) * 1e6)
            with lat_lock:
                lat_us.extend(local)
            cli.close()

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(ns.threads)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        run_s = time.perf_counter() - t0

        lat_us.sort()
        n = len(lat_us)
        result = {
            "metric": f"YCSB-A 50/50 read-update, {ns.partitions} partitions, "
                      f"{ns.threads} threads, {ns.records} records",
            "value": round(n / run_s, 1),
            "unit": "ops/s",
            "detail": {
                "load_s": round(load_s, 2),
                "load_ops_s": round(ns.records / load_s, 1),
                "run_s": round(run_s, 2),
                "avg_us": round(sum(lat_us) / max(1, n), 1),
                "p99_us": round(lat_us[min(n - 1, int(n * 0.99))] if lat_us else 0, 1),
                "errors": errors[0],
            },
        }
        print(json.dumps(result))

    finally:
        if cluster is not None:
            cluster.stop()


if __name__ == "__main__":
    main()
