"""`python -m tools.fsck <dir>...` — offline data-dir integrity check.

The cold half of the self-healing plane (ISSUE 17): the background scrub
re-verifies LIVE replicas; this walks data dirs on disk with the engine
stopped — post-incident forensics, pre-restart sanity, and the
pressure_test harness's final quiesced sweep over every surviving
replica.

Each argument is either one engine data dir (contains a ``MANIFEST``) or
a replica/node root to walk recursively for data dirs. For every data
dir it verifies:

* every ``*.sst``'s magic, header parse, and per-section crc32
  (truncated / zero-length / bit-flipped files are typed findings, via
  the same ``verify_sst`` the scrub uses — legacy headers without
  checksums pass structurally, exactly like the read path);
* every MANIFEST-referenced file exists (``manifest_missing``);
* every on-disk SST is MANIFEST-referenced (``orphan`` — INFO only: the
  engine adopts or ignores orphans at open, they are waste, not rot).

Exit 0 when no error-level findings (orphans alone stay exit 0);
``--json`` prints the machine-readable findings list on stdout.
"""

import argparse
import glob
import json
import os
import sys


def _is_data_dir(path: str) -> bool:
    return os.path.isfile(os.path.join(path, "MANIFEST")) \
        or bool(glob.glob(os.path.join(path, "*.sst")))


def find_data_dirs(root: str) -> list:
    """`root` itself if it is a data dir, else every data dir below it."""
    if _is_data_dir(root):
        return [root]
    out = []
    for dirpath, dirnames, _ in os.walk(root):
        # a quarantined tree is forensics: already known-bad, skip it
        dirnames[:] = [d for d in dirnames if d != "quarantine"]
        if _is_data_dir(dirpath):
            out.append(dirpath)
            dirnames[:] = []
    return sorted(out)


def fsck_data_dir(path: str) -> list:
    """-> findings: [{"dir", "kind", "path", "detail", "severity"}].

    kinds: ``corrupt`` (bad magic / truncated / crc mismatch, error),
    ``manifest`` (unreadable MANIFEST, error), ``manifest_missing``
    (referenced file absent, error), ``orphan`` (unreferenced SST,
    info)."""
    from pegasus_tpu.engine.sstable import CorruptionError, verify_sst

    findings = []

    def add(kind, p, detail, severity="error"):
        findings.append({"dir": path, "kind": kind, "path": p,
                         "detail": detail, "severity": severity})

    referenced = set()
    mpath = os.path.join(path, "MANIFEST")
    if os.path.isfile(mpath):
        try:
            with open(mpath) as f:
                manifest = json.load(f)
            names = list(manifest.get("l0", []))
            for files in manifest.get("levels", {}).values():
                names.extend(files)
            for name in names:
                referenced.add(name)
                if not os.path.isfile(os.path.join(path, name)):
                    add("manifest_missing", os.path.join(path, name),
                        "MANIFEST references a file that does not exist")
        except (ValueError, KeyError, TypeError, OSError, AttributeError) as e:
            add("manifest", mpath, f"unreadable MANIFEST: {e!r}")
    for sst in sorted(glob.glob(os.path.join(path, "*.sst"))):
        try:
            verify_sst(sst)
        except CorruptionError as e:
            add("corrupt", sst, e.detail)
        except OSError as e:
            add("corrupt", sst, f"unreadable: {e!r}")
        if os.path.basename(sst) not in referenced:
            add("orphan", sst, "SST not referenced by MANIFEST "
                "(engine-open adopts or ignores it — waste, not rot)",
                severity="info")
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.fsck",
        description="offline SST/manifest integrity check")
    ap.add_argument("roots", nargs="+",
                    help="engine data dir(s) or replica/node root(s)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    args = ap.parse_args(argv)
    findings, dirs = [], []
    for root in args.roots:
        if not os.path.exists(root):
            findings.append({"dir": root, "kind": "missing", "path": root,
                             "detail": "no such directory",
                             "severity": "error"})
            continue
        for d in find_data_dirs(root):
            dirs.append(d)
            findings.extend(fsck_data_dir(d))
    errors = [f for f in findings if f["severity"] == "error"]
    if args.json:
        print(json.dumps({"dirs": dirs, "findings": findings,
                          "errors": len(errors)}, indent=2))
    else:
        for f in findings:
            print(f"fsck: [{f['severity']}] {f['kind']} {f['path']}: "
                  f"{f['detail']}", file=sys.stderr)
        print(f"fsck: {'FAIL' if errors else 'OK'} — {len(dirs)} data "
              f"dir(s), {len(findings)} finding(s), {len(errors)} error(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
