#!/usr/bin/env python
"""Thin CLI shim over tools/analyze/metric_names.py (the metric-name
cross-check now lives in the shared static-analysis framework; run
`python -m tools.analyze` for the whole plane). Kept so existing
invocations — tests/test_tools.py runs this script and monkeypatches
`source_metric_names` / `readme_metric_rows` — keep working."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.analyze import Repo  # noqa: E402
from tools.analyze import metric_names as _pass  # noqa: E402

_REPO = Repo()


def source_metric_names() -> set:
    return _pass.source_metric_names(_REPO)


def readme_metric_rows() -> list:
    return _pass.readme_metric_rows(_REPO)


def run_lint() -> list:
    """-> list of error strings (empty = clean). Reads the collectors
    through THIS module so monkeypatched tests keep their teeth."""
    return [f.message for f in
            _pass.lint_findings(source_metric_names(),
                                readme_metric_rows(), _REPO.readme)]


def main() -> int:
    errors = run_lint()
    for e in errors:
        print(f"check_metric_names: {e}", file=sys.stderr)
    if not errors:
        print(f"check_metric_names: OK "
              f"({len(source_metric_names())} counter names)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
