#!/usr/bin/env python
"""Metric-name cross-check lint (wired into the test run via
tests/test_tools.py), the counter-registry twin of check_fail_points.py:

every perf-counter name registered in source
(``counters.rate/percentile/number/volatile_number("name")``) must be
DOCUMENTED in README.md's Observability metric tables — counters nobody
can discover rot, and a renamed counter silently breaks every dashboard
scraping the old name.

The REVERSE direction is linted too: every row of README's metric-name
table must still have a matching counter registration in source — a
deleted or renamed counter whose row stays behind documents a metric no
scrape will ever return, which is worse than no documentation. Row names
normalize `<placeholder>` holes to wildcards and split ``a / b`` and
``a\|b`` cells into variants; each variant's longest literal segment is
probed against the set of registered names (the mirror of the forward
probe).

Dynamic names become wildcards: f-string holes
(``f"profiler.{code}.qps"`` -> ``profiler.*.qps``) and concatenated
prefixes (``self._pfx + "put_qps"`` -> ``*.put_qps``). For each name the
longest literal segment (dots trimmed) is probed against README.md, so
``*.put_qps`` requires ``put_qps`` to appear and
``collector.app.*.hotkey.*`` requires ``collector.app.`` or ``hotkey``
(whichever is longer) to appear.
"""

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# a counter registration call; the name argument is parsed from here on
_KIND_RE = re.compile(
    r"counters\.(?:rate|percentile|number|volatile_number)\(")
# <prefix-expr> +  (e.g. self._pfx + "put_qps") -> leading wildcard
_PFX_RE = re.compile(r"\s*[A-Za-z_][\w.]*\s*\+\s*")
# one (f-)string literal; `\s*` spans newlines, so adjacent literals in a
# multi-line implicit concatenation chain all parse
_STR_RE = re.compile(r"\s*(f?)\"([^\"]*)\"")
_JOIN_RE = re.compile(r"\s*\+\s*")


def _wildcard(is_fstring: str, name: str) -> str:
    if is_fstring:
        name = re.sub(r"\{[^}]*\}", "*", name)
    return name


def _name_at(text: str, pos: int) -> str:
    """Parse the counter-name expression starting at `pos` (just past the
    opening paren) into a wildcard pattern: f-string holes and non-literal
    sub-expressions become '*', adjacent/'+'-joined literals concatenate.
    Returns '' when the argument holds no string literal at all."""
    prefix = ""
    mp = _PFX_RE.match(text, pos)
    if mp:
        prefix, pos = "*", mp.end()
    parts = []
    while True:
        ms = _STR_RE.match(text, pos)
        if not ms:
            break
        parts.append(_wildcard(ms.group(1), ms.group(2)))
        pos = ms.end()
        mj = _JOIN_RE.match(text, pos)
        if mj:
            if _STR_RE.match(text, mj.end()):
                pos = mj.end()
            else:  # '+ expr' with a non-literal tail
                parts.append("*")
                break
    return prefix + "".join(parts) if parts else ""


def source_metric_names() -> set:
    names = set()
    files = list((REPO / "pegasus_tpu").rglob("*.py")) + [REPO / "bench.py"]
    for p in files:
        text = p.read_text()
        for m in _KIND_RE.finditer(text):
            name = _name_at(text, m.end())
            if name:
                names.add(name)
    return names


def _probe(name: str) -> str:
    """Longest wildcard-free segment of the name (dots trimmed) — what
    must literally appear in the README's metric tables."""
    segments = [s.strip(".") for s in name.split("*")]
    segments = [s for s in segments if s]
    return max(segments, key=len, default="")


def readme_metric_rows() -> list:
    """Counter-name variants from README's '### Metric-name table'
    section: one entry per backticked span in each row's first cell,
    split on ' / ' and '\\|' alternations, `<placeholder>` -> '*'."""
    text = (REPO / "README.md").read_text()
    m = re.search(r"^### Metric-name table$(.*?)^## ", text,
                  re.MULTILINE | re.DOTALL)
    section = m.group(1) if m else ""
    rows = []
    for line in section.splitlines():
        if not line.startswith("|"):
            continue
        cells = line.split("|")
        if len(cells) < 3 or set(cells[1].strip()) <= {"-", " "}:
            continue  # separator / malformed row
        for span in re.findall(r"`([^`]+)`", cells[1]):
            for variant in re.split(r"\\\||/", span):
                variant = variant.strip()
                if variant:
                    rows.append(re.sub(r"<[^>]*>", "*", variant))
    return rows


def run_lint() -> list:
    """-> list of error strings (empty = clean)."""
    readme = (REPO / "README.md").read_text()
    errors = []
    src = source_metric_names()
    for name in sorted(src):
        probe = _probe(name)
        if probe and probe not in readme:
            errors.append(
                f"source counter {name!r} is undocumented — add it to "
                f"README.md's Observability metric tables "
                f"(probe segment {probe!r} not found)")
    # reverse pass: a README row must still name a registered counter
    haystack = "\n".join(sorted(src))
    for row in readme_metric_rows():
        probe = _probe(row)
        if probe and probe not in haystack:
            errors.append(
                f"README metric row {row!r} has no matching counter "
                f"registration in source (probe segment {probe!r}) — "
                f"delete the row or restore the counter")
    return errors


def main() -> int:
    errors = run_lint()
    for e in errors:
        print(f"check_metric_names: {e}", file=sys.stderr)
    if not errors:
        print(f"check_metric_names: OK "
              f"({len(source_metric_names())} counter names)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
