#!/usr/bin/env python
"""Metric-name cross-check lint (wired into the test run via
tests/test_tools.py), the counter-registry twin of check_fail_points.py:

every perf-counter name registered in source
(``counters.rate/percentile/number/volatile_number("name")``) must be
DOCUMENTED in README.md's Observability metric tables — counters nobody
can discover rot, and a renamed counter silently breaks every dashboard
scraping the old name.

Dynamic names become wildcards: f-string holes
(``f"profiler.{code}.qps"`` -> ``profiler.*.qps``) and concatenated
prefixes (``self._pfx + "put_qps"`` -> ``*.put_qps``). For each name the
longest literal segment (dots trimmed) is probed against README.md, so
``*.put_qps`` requires ``put_qps`` to appear and
``collector.app.*.hotkey.*`` requires ``collector.app.`` or ``hotkey``
(whichever is longer) to appear.
"""

import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# literal / f-string first argument
_LIT_RE = re.compile(
    r"counters\.(?:rate|percentile|number|volatile_number)\(\s*\n?\s*(f?)\"([^\"]+)\"")
# <prefix-expr> + "literal" first argument (e.g. self._pfx + "put_qps")
_CAT_RE = re.compile(
    r"counters\.(?:rate|percentile|number|volatile_number)\(\s*\n?\s*"
    r"[A-Za-z_][\w.]*\s*\+\s*(f?)\"([^\"]+)\"")


def _wildcard(is_fstring: str, name: str) -> str:
    if is_fstring:
        name = re.sub(r"\{[^}]*\}", "*", name)
    return name


def source_metric_names() -> set:
    names = set()
    files = list((REPO / "pegasus_tpu").rglob("*.py")) + [REPO / "bench.py"]
    for p in files:
        text = p.read_text()
        for m in _LIT_RE.finditer(text):
            names.add(_wildcard(m.group(1), m.group(2)))
        for m in _CAT_RE.finditer(text):
            names.add("*" + _wildcard(m.group(1), m.group(2)))
    return names


def _probe(name: str) -> str:
    """Longest wildcard-free segment of the name (dots trimmed) — what
    must literally appear in the README's metric tables."""
    segments = [s.strip(".") for s in name.split("*")]
    segments = [s for s in segments if s]
    return max(segments, key=len, default="")


def run_lint() -> list:
    """-> list of error strings (empty = clean)."""
    readme = (REPO / "README.md").read_text()
    errors = []
    for name in sorted(source_metric_names()):
        probe = _probe(name)
        if probe and probe not in readme:
            errors.append(
                f"source counter {name!r} is undocumented — add it to "
                f"README.md's Observability metric tables "
                f"(probe segment {probe!r} not found)")
    return errors


def main() -> int:
    errors = run_lint()
    for e in errors:
        print(f"check_metric_names: {e}", file=sys.stderr)
    if not errors:
        print(f"check_metric_names: OK "
              f"({len(source_metric_names())} counter names)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
