#!/usr/bin/env python
"""Tracing-overhead microbench (ROADMAP open item: quantify span cost
before revisiting PEGASUS_TRACE_SAMPLE_EVERY).

Measures, at high event rates:
  - StageTracer.span close cost (the compaction pipeline's per-stage
    probe: ring append + 2-4 counter updates + optional session add);
  - StageTracer.event cost (the pipeline's synthetic overlap records);
  - RequestTracer root+span cost (the serving path's per-request trace:
    what PEGASUS_TRACE_SAMPLE_EVERY gates).

Prints ONE json line, e.g.
  {"stage_span_us": ..., "stage_span_in_session_us": ...,
   "stage_event_us": ..., "request_trace_us": ..., "n": ...}

Per-span cost is amortized wall time over PEGASUS_TRACE_BENCH_N
iterations (default 100_000; the RequestTracer loop runs n/10 — each
iteration is a whole root trace). Interpreting the result: a compaction
span wraps work in the 10ms..10s range, so ~10us/span is noise (<0.1%);
a request trace costs ~3 spans on a put whose floor is ~100us of real
work — raise PEGASUS_TRACE_SAMPLE_EVERY only if profiles show the
tracer inside the top write-path costs at target QPS.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_stage_span(n: int, in_session: bool) -> float:
    from pegasus_tpu.runtime.tracing import StageTracer

    tr = StageTracer(prefix="t_overhead")

    def loop():
        t0 = time.perf_counter()
        for _ in range(n):
            with tr.span("device", records=1, nbytes=64):
                pass
        return (time.perf_counter() - t0) / n

    if not in_session:
        return loop()
    with tr.session():
        return loop()


def bench_stage_event(n: int) -> float:
    from pegasus_tpu.runtime.tracing import StageTracer

    tr = StageTracer(prefix="t_overhead_ev")
    t0 = time.perf_counter()
    for _ in range(n):
        tr.event("pipeline.overlap", 0.001)
    return (time.perf_counter() - t0) / n


def bench_event_emit(n: int) -> float:
    """events.emit cost (ISSUE 12): the flight recorder's bus sits on
    transition edges of hot paths (breaker trips, throttle engage), and
    tier-1 runs with it always-on — it must stay ~as cheap as a counter
    increment."""
    from pegasus_tpu.runtime.events import EventBus

    bus = EventBus(capacity=4096)
    t0 = time.perf_counter()
    for i in range(n):
        bus.emit("lane.fallback", severity="warn", lane="compact.lane",
                 op="compact")
    return (time.perf_counter() - t0) / n


def bench_history_sample(n: int) -> float:
    """One metric-history sample (full registry snapshot + prefix filter
    + ring store): runs every PEGASUS_HISTORY_INTERVAL_S per process, so
    even a millisecond-scale cost is ~0.02% duty at the 5 s default."""
    from pegasus_tpu.runtime.metric_history import MetricHistory
    from pegasus_tpu.runtime.perf_counters import counters

    # a realistic registry slice for the sampler to walk
    for i in range(40):
        counters.rate(f"engine.overheadbench.{i}.count").increment()
    h = MetricHistory(interval_s=5, capacity=720)
    t0 = time.perf_counter()
    for _ in range(n):
        h.sample_once()
    dur = (time.perf_counter() - t0) / n
    for i in range(40):
        counters.remove(f"engine.overheadbench.{i}.count")
    return dur


def bench_table_ledger(n: int) -> float:
    """One per-request table-ledger charge (ISSUE 18): the tenant plane
    bills every served read on the hot path (a rate increment, a
    percentile set, a bytes-out add against pre-resolved counters), so
    its cost must stay in the same noise band as the request tracer."""
    from pegasus_tpu.runtime.table_stats import TABLE_STATS

    led = TABLE_STATS.ledger("t_overhead_bench")
    t0 = time.perf_counter()
    for _ in range(n):
        led.charge_read(120, 64)
    dur = (time.perf_counter() - t0) / n
    TABLE_STATS.reset()
    return dur


def bench_request_trace(n: int) -> float:
    from pegasus_tpu.runtime.tracing import RequestTracer

    rt = RequestTracer()
    t0 = time.perf_counter()
    for _ in range(n):
        with rt.root("put"):
            with rt.span("rpc.put"):
                with rt.span("engine.write"):
                    pass
    return (time.perf_counter() - t0) / n


def run(n: int = None) -> dict:
    n = n or int(os.environ.get("PEGASUS_TRACE_BENCH_N", 100_000))
    return {
        "n": n,
        "stage_span_us": round(bench_stage_span(n, False) * 1e6, 2),
        "stage_span_in_session_us": round(
            bench_stage_span(n, True) * 1e6, 2),
        "stage_event_us": round(bench_stage_event(n) * 1e6, 2),
        # one request trace = root + 2 nested spans + finalize
        "request_trace_us": round(
            bench_request_trace(max(1, n // 10)) * 1e6, 2),
        # tenant plane (ISSUE 18): one per-request table-ledger charge
        "table_ledger_us": round(bench_table_ledger(n) * 1e6, 2),
        # flight recorder (ISSUE 12): event emit + one history sample
        "event_emit_us": round(bench_event_emit(n) * 1e6, 2),
        "history_sample_us": round(
            bench_history_sample(max(1, n // 100)) * 1e6, 2),
    }


if __name__ == "__main__":
    print(json.dumps(run()))
