"""One-shot real-TPU validation + benchmark session.

Run when the axon tunnel is alive (it wedges for hours when poked while
dead, so this probes first, in a bounded subprocess). Stages, each gated
on the previous and individually time-bounded:

  1. probe     — backend init + tiny matmul in a subprocess
  2. kernels   — small-N byte-equality: cpu vs tpu (network path), cached
                 device-run path, and PEGASUS_PALLAS=1 merge-path kernel
  3. bench     — bench.py at PEGASUS_BENCH_N (default 10M), both with and
                 without pallas, recording both JSON lines
  4. engine    — tools/engine_bench.py at PEGASUS_EBENCH_N (default 2M)

Every stage's JSON/result lines append to TPU_SESSION.log next to this
repo so a dropped tunnel mid-way still leaves the completed stages
recorded. Nothing here SIGKILLs a TPU-attached process: stage timeouts
use SIGTERM and generous budgets.

Usage: python tools/tpu_session.py [--stages probe,kernels,bench,engine]
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_SESSION.log")


def log(line: str):
    stamp = time.strftime("%Y-%m-%d %H:%M:%S")
    with open(LOG, "a") as f:
        f.write(f"[{stamp}] {line}\n")
    print(f"[{stamp}] {line}", flush=True)


_RUN_SEQ = [0]


def run(cmd, timeout_s, env_extra=None, label=""):
    env = dict(os.environ)
    env.update(env_extra or {})
    log(f"RUN {label or cmd}: timeout {timeout_s}s env {env_extra}")
    # child output goes to FILES, never pipes: an abandoned child blocked
    # on a full unread pipe could never exit and would hold the device
    # lease forever
    _RUN_SEQ[0] += 1
    base = os.path.join(REPO, f".tpu_session_{_RUN_SEQ[0]:02d}")
    with open(base + ".out", "wb") as fo, open(base + ".err", "wb") as fe:
        proc = subprocess.Popen(cmd, cwd=REPO, env=env, stdout=fo, stderr=fe)
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            # NEVER SIGKILL a TPU-attached process (it wedges the tunnel's
            # device lease for hours): SIGTERM, grace-wait, and if it still
            # won't die, ABANDON it and move on
            proc.terminate()
            try:
                proc.wait(timeout=60)
                log(f"TIMEOUT {label} (terminated cleanly)")
            except subprocess.TimeoutExpired:
                log(f"TIMEOUT {label} — child ignored SIGTERM; ABANDONED "
                    f"(pid {proc.pid}), not killing a TPU-attached process")
            return None
    with open(base + ".out", "r", errors="replace") as f:
        stdout = f.read()
    with open(base + ".err", "r", errors="replace") as f:
        stderr = f.read()
    for line in stdout.strip().splitlines()[-6:]:
        log(f"  out: {line}")
    if proc.returncode != 0:
        for line in stderr.strip().splitlines()[-4:]:
            log(f"  err: {line}")
        log(f"FAIL {label} rc={proc.returncode}")
        return None
    os.unlink(base + ".out")
    os.unlink(base + ".err")
    return stdout


def stage_probe() -> bool:
    out = run([sys.executable, "-c",
               "import jax, jax.numpy as jnp;"
               "print('PLATFORM:', jax.devices()[0]);"
               "print('SUM:', int(jnp.arange(64).sum()))"],
              timeout_s=180, label="probe")
    ok = out is not None and "SUM: 2016" in out
    log(f"probe: {'ALIVE' if ok else 'DEAD'}")
    return ok


def stage_kernels() -> tuple:
    code = (
        "import numpy as np\n"
        "from pegasus_tpu.base.utils import enable_compile_cache\n"
        "enable_compile_cache(%r)\n"
        "import tests.test_compact_ops as t\n"
        "from pegasus_tpu.ops.compact import (CompactOptions, compact_blocks,"
        " pack_run_device, sort_block)\n"
        "rng = np.random.default_rng(5)\n"
        "recs = [(b'u%%05d' %% rng.integers(0, 300), b's%%d' %% (i %% 5),"
        " b'v%%d' %% i, 0, bool(rng.random() < .1)) for i in range(3000)]\n"
        "runs = [sort_block(t.make_block(p), CompactOptions(backend='cpu'))"
        " for p in (recs[:1500], recs[1500:])]\n"
        "o = dict(now=100, bottommost=True, runs_sorted=True)\n"
        "cpu = compact_blocks(runs, CompactOptions(backend='cpu', **o))\n"
        "tpu = compact_blocks(runs, CompactOptions(backend='tpu', **o))\n"
        "drs = [pack_run_device(b) for b in runs]\n"
        "cch = compact_blocks(runs, CompactOptions(backend='tpu', **o),"
        " device_runs=drs)\n"
        "for x in (tpu, cch):\n"
        "    assert np.array_equal(cpu.block.key_arena, x.block.key_arena)\n"
        "    assert np.array_equal(cpu.block.val_arena, x.block.val_arena)\n"
        "print('KERNELS_BYTE_EQUAL')\n" % REPO)
    ok1 = run([sys.executable, "-c", code], timeout_s=900,
              label="kernels:xla+cached") is not None
    ok2 = run([sys.executable, "-c", code], timeout_s=900,
              env_extra={"PEGASUS_PALLAS": "1"},
              label="kernels:pallas") is not None
    log(f"kernels: xla/cached {'OK' if ok1 else 'FAIL'}, "
        f"pallas {'OK' if ok2 else 'FAIL'}")
    if ok1 and not ok2:
        log("pallas FAILED on hardware — keep PEGASUS_PALLAS default off")
    return ok1, ok2


def stage_bench(pallas_ok: bool):
    n = os.environ.get("PEGASUS_BENCH_N", "10000000")
    run([sys.executable, "bench.py"], timeout_s=3000,
        env_extra={"PEGASUS_BENCH_N": n}, label=f"bench N={n}")
    if pallas_ok:
        run([sys.executable, "bench.py"], timeout_s=3000,
            env_extra={"PEGASUS_BENCH_N": n, "PEGASUS_PALLAS": "1"},
            label=f"bench N={n} pallas")


def stage_engine():
    n = os.environ.get("PEGASUS_EBENCH_N", "2000000")
    run([sys.executable, "tools/engine_bench.py"], timeout_s=3000,
        env_extra={"PEGASUS_EBENCH_N": n}, label=f"engine_bench N={n}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", default="probe,kernels,bench,engine")
    args = ap.parse_args()
    stages = args.stages.split(",")
    log(f"=== tpu_session start (stages: {stages}) ===")
    if "probe" in stages and not stage_probe():
        log("=== aborted: tunnel dead ===")
        sys.exit(3)
    # pallas only ever benches AFTER the kernels stage validated it on this
    # hardware — skipping the kernels stage keeps it off
    pallas_ok = False
    if "kernels" in stages:
        code_ok, pallas_ok = stage_kernels()
        if not code_ok:
            log("=== aborted: kernel validation failed ===")
            sys.exit(4)
    if "bench" in stages:
        stage_bench(pallas_ok)
    if "engine" in stages:
        stage_engine()
    log("=== tpu_session done ===")


if __name__ == "__main__":
    main()
