"""Geo benchmark: set_geo_data fill + radial search latency + geo compact.

The BASELINE.json 'geo range-scan + compact' report row (reference
src/geo benchmarks its S2-indexed radial query path). Boots an in-process
MiniCluster, fills N points in a metro-sized box, measures search_radial
latency over random centers, then manual-compacts both geo tables.

Usage: python tools/geo_bench.py   (env: PEGASUS_GEOBENCH_N, _QUERIES,
_RADIUS_M)
"""

import json
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    n = int(os.environ.get("PEGASUS_GEOBENCH_N", 20_000))
    n_queries = int(os.environ.get("PEGASUS_GEOBENCH_QUERIES", 200))
    radius_m = float(os.environ.get("PEGASUS_GEOBENCH_RADIUS_M", 500))

    from pegasus_tpu.client import MetaResolver, PegasusClient
    from pegasus_tpu.geo.geo_client import GeoClient
    from tests.test_satellites import MiniCluster

    rng = random.Random(7)
    with tempfile.TemporaryDirectory() as root:
        import pathlib

        c = MiniCluster(pathlib.Path(root), n_nodes=3)
        try:
            c.create("geo_main", partitions=4).close()
            c.create("geo_idx", partitions=4).close()
            geo = GeoClient(
                PegasusClient(MetaResolver([c.meta_addr], "geo_main")),
                PegasusClient(MetaResolver([c.meta_addr], "geo_idx")),
                max_level=int(os.environ.get("PEGASUS_GEO_MAX_LEVEL", 16)),
                scan_threads=int(os.environ.get("PEGASUS_GEO_THREADS", 8)))
            # fill: a ~20km box around 40.06N 116.4E (the reference's
            # bench geography)
            t0 = time.perf_counter()
            for i in range(n):
                lat = 40.06 + rng.uniform(-0.1, 0.1)
                lng = 116.40 + rng.uniform(-0.1, 0.1)
                geo.set_geo_data(lat, lng, b"p%07d" % i, b"s", b"v%d" % i)
            fill_s = time.perf_counter() - t0
            # radial queries
            lat_ms = []
            found_total = 0
            for _ in range(n_queries):
                lat = 40.06 + rng.uniform(-0.08, 0.08)
                lng = 116.40 + rng.uniform(-0.08, 0.08)
                t0 = time.perf_counter()
                rows = geo.search_radial(lat, lng, radius_m, count=100)
                lat_ms.append((time.perf_counter() - t0) * 1000)
                found_total += len(rows)
            lat_ms.sort()
            # compact both geo tables through the serving stack
            t0 = time.perf_counter()
            for stub in c.stubs:
                for rep in list(stub._replicas.values()):
                    rep.server.engine.manual_compact(now=100)
            compact_s = time.perf_counter() - t0
            print(json.dumps({
                "metric": f"geo radial search p50 latency ({n} points, "
                          f"{radius_m:.0f}m radius)",
                "value": round(lat_ms[len(lat_ms) // 2], 2),
                "unit": "ms",
                "detail": {
                    "fill_s": round(fill_s, 2),
                    "fill_points_per_s": int(n / fill_s),
                    "queries": n_queries,
                    "p95_ms": round(lat_ms[int(len(lat_ms) * 0.95)], 2),
                    "avg_results_per_query": round(found_total / n_queries, 1),
                    "geo_tables_compact_s": round(compact_s, 2),
                },
            }), flush=True)
        finally:
            c.stop()


if __name__ == "__main__":
    main()
