"""North-star-scale benchmark: BASELINE.json's 100M-key fillrandom+compact
config (reference pegasus_bench fillrandom + manual compact over a 100M-key
table), exercising the bigger-than-device blockwise path at the scale it
was built for (VERDICT-r3 item 5).

Unlike bench.py (which times the raw backend lanes), both lanes here go
through ops.compact.compact_blocks — so with PEGASUS_SCALE_MAXDEV below the
input size the device lane takes `_compact_blockwise` (ops/compact.py:651):
disjoint key ranges compacted independently, outputs concatenated, the
byte-equality contract checked against the native CPU lane's digest.

Bounded like every tool in tools/ (VERDICT-r3 item 8): a watchdog thread
hard-exits with a parseable degraded JSON line after
PEGASUS_SCALE_TIMEOUT_S (default 5400 s — the 100M fill alone is ~5 min on
the 1-core dev host), and the device lane also honors
PEGASUS_SCALE_FAKE=sleep (test hook simulating a wedged device mid-lane).

Env: PEGASUS_SCALE_N (default 100_000_000), PEGASUS_SCALE_MAXDEV (default
16M records — forces ~13 range blocks at 100M), PEGASUS_SCALE_RUNS (4),
PEGASUS_SCALE_VALUE (100), PEGASUS_SCALE_TIMEOUT_S, JAX_PLATFORMS=cpu for
a host-only run when the TPU tunnel is down.
"""

import hashlib
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_PRINTED = False


def _emit(result: dict) -> None:
    global _PRINTED
    if _PRINTED:
        return
    _PRINTED = True
    print(json.dumps(result), flush=True)


def _params():
    return (int(os.environ.get("PEGASUS_SCALE_N", 100_000_000)),
            int(os.environ.get("PEGASUS_SCALE_RUNS", 4)),
            int(os.environ.get("PEGASUS_SCALE_VALUE", 100)),
            int(os.environ.get("PEGASUS_SCALE_MAXDEV", 16 << 20)))


def _metric(n, n_runs, value_size, maxdev) -> str:
    return (f"blockwise fillrandom+compact at north-star scale "
            f"({n} records, {n_runs} runs, value={value_size}B, "
            f"max_device_records={maxdev})")


_PROGRESS = {}


def _arm_watchdog():
    import threading

    budget = int(os.environ.get("PEGASUS_SCALE_TIMEOUT_S", 5400))
    if budget <= 0:
        return

    def boom():
        n, n_runs, value_size, maxdev = _params()
        _emit({"metric": _metric(n, n_runs, value_size, maxdev),
               "value": None, "unit": "x", "vs_baseline": None,
               "detail": {"degraded": True,
                          "reason": f"watchdog fired after {budget}s",
                          **_PROGRESS}})
        os._exit(0)

    t = threading.Timer(budget, boom)
    t.daemon = True
    t.start()


def _digest(block) -> dict:
    return {"n_out": int(block.n),
            "key_sha": hashlib.sha256(block.key_arena).hexdigest(),
            "val_sha": hashlib.sha256(block.val_arena).hexdigest()}


def main():
    _arm_watchdog()
    n, n_runs, value_size, maxdev = _params()

    import bench  # reuse the deterministic vectorized fill

    from pegasus_tpu.ops.compact import CompactOptions, compact_blocks

    t0 = time.perf_counter()
    runs, fill_s = bench._fill(n, n_runs, value_size)
    _PROGRESS["fill_s"] = round(fill_s, 3)
    print(f"scale: filled {n} records in {fill_s:.1f}s",
          file=sys.stderr, flush=True)

    cpu_opts = CompactOptions(backend="cpu", now=100, bottommost=True,
                              runs_sorted=True)
    t1 = time.perf_counter()
    cpu = compact_blocks(runs, cpu_opts)
    cpu_s = time.perf_counter() - t1
    cpu_dig = _digest(cpu.block)
    del cpu
    _PROGRESS.update(cpu_compact_s=round(cpu_s, 3),
                     output_records=cpu_dig["n_out"])
    print(f"scale: cpu lane {cpu_s:.1f}s "
          f"({int(n / cpu_s)} rec/s, {cpu_dig['n_out']} survivors)",
          file=sys.stderr, flush=True)

    if os.environ.get("PEGASUS_SCALE_FAKE") == "sleep":
        time.sleep(3600)  # test hook: device lane wedges

    from pegasus_tpu.base.utils import enable_compile_cache

    enable_compile_cache(REPO)
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    platform = str(jax.devices()[0])
    dev_opts = CompactOptions(backend="tpu", now=100, bottommost=True,
                              runs_sorted=True, max_device_records=maxdev)
    assert n > maxdev, "device lane would not take the blockwise path"
    t2 = time.perf_counter()
    dev = compact_blocks(runs, dev_opts)
    dev_s = time.perf_counter() - t2
    dev_dig = _digest(dev.block)
    del dev

    byte_equal = dev_dig == cpu_dig
    speedup = cpu_s / dev_s
    _emit({
        "metric": _metric(n, n_runs, value_size, maxdev),
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup, 3),
        "detail": {
            "fill_s": round(fill_s, 3),
            "cpu_compact_s": round(cpu_s, 3),
            "device_compact_s": round(dev_s, 3),
            "input_records": n,
            "output_records": cpu_dig["n_out"],
            "byte_equal": byte_equal,
            "platform": platform,
            "blocks": -(-n // maxdev),
            "total_s": round(time.perf_counter() - t0, 1),
        },
    })
    if not byte_equal:
        sys.exit(3)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 - always leave a parseable line
        import traceback

        traceback.print_exc()
        n, n_runs, value_size, maxdev = _params()
        _emit({"metric": _metric(n, n_runs, value_size, maxdev),
               "value": None, "unit": "x", "vs_baseline": None,
               "detail": {"degraded": True,
                          "reason": f"{type(e).__name__}: {str(e)[:300]}",
                          **_PROGRESS}})
        sys.exit(0)
