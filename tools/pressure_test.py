"""Pressure test: sustained target-QPS load — now with a chaos scenario
engine (ISSUE 11's production-sim harness).

The reference's src/test/pressure_test + kill_test tiers in one driver: a
load generator holding a TARGET qps against a cluster with a configurable
op mix (point gets, RANGE reads — bounded multi_gets plus a periodic
full-table unordered-scanner sweep, exercising the device-served range
path under faults — and writes), writing SELF-CHECKING rows (value
derived from key) so every read verifies itself, while (optionally) a
scripted fault schedule runs
node kills, group-worker kills, remote fail-point wedges, a mid-load
partition split, a balancer primary move, compaction-scheduler token
flips and a duplication leg to a second cluster — all under periodic
decree-anchored audit rounds.

Pass criterion (exit 0) — every failure is NAMED in the event journal:

  * zero lost acked writes (self-verifying reads, with re-read
    verification before anything counts as lost);
  * every transient error fell inside a DECLARED fault window
    (steady-state errors fail the run);
  * every audit round mismatch-free, with at least one conclusive
    (non-vacuous) round;
  * scenario runs: every fault healed within its recovery deadline, the
    cross-cluster digest compare (anchored at the duplicator's confirmed
    decree) matched, and the final cluster_doctor verdict is healthy.

Usage:
    python tools/pressure_test.py [--meta host:port] [--table t]
        [--qps 500] [--seconds 30] [--threads 4] [--read-pct 50]
        [--scenario none|smoke|full] [--audit-every 5] [--journal out.json]
(no --meta: boots its own onebox; --scenario requires the self-booted
onebox — the fault actors need the cluster handles)
"""

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def expected_value(key: bytes) -> bytes:
    import hashlib

    return hashlib.md5(key).hexdigest().encode()


class LatencyReservoir:
    """Bounded-memory latency sample (Vitter's Algorithm R) replacing the
    old unbounded per-op list: a long chaos run at 500+ QPS would hold
    millions of floats. Up to `cap` samples the reservoir IS the full
    population, so `percentile` reproduces the old sorted-list semantics
    exactly (index ``min(n-1, int(n*p))``); past `cap` each op keeps a
    uniform cap/count chance of being sampled. Thread-safe."""

    def __init__(self, cap: int = 8192, seed: int = 0):
        self.cap = max(1, cap)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._sample = []
        self.count = 0
        self.total = 0.0

    def add(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self.total += v
            if len(self._sample) < self.cap:
                self._sample.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self.cap:
                    self._sample[j] = v

    def percentile(self, p: float) -> float:
        with self._lock:
            s = sorted(self._sample)
        if not s:
            return 0.0
        return round(s[min(len(s) - 1, int(len(s) * p))], 2)

    def avg(self) -> float:
        with self._lock:
            return round(self.total / self.count, 2) if self.count else 0.0


def _parse_args(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--meta", default="")
    ap.add_argument("--table", default="pressure")
    ap.add_argument("--qps", type=int, default=500)
    ap.add_argument("--seconds", type=float, default=30.0)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--read-pct", type=int, default=50)
    ap.add_argument("--scan-pct", type=int, default=10,
                    help="share of ops that are RANGE reads — a bounded "
                         "multi_get over the hash key's sortkey range, "
                         "carved out of the write share — so the "
                         "device-served range path (ISSUE 19) runs under "
                         "node kills, splits and audits; also enables a "
                         "periodic full-table unordered-scanner sweep on "
                         "thread 0 (every row self-verifies, with re-read "
                         "verification before anything counts); 0 "
                         "disables both")
    ap.add_argument("--key-space", type=int, default=100_000)
    ap.add_argument("--tables", type=int, default=1,
                    help="number of tables to load (table, table2..tableN; "
                         "a self-booted onebox creates the extras): each "
                         "table gets a DISTINCT key prefix and a skewed "
                         "share of the op mix (table k weighted 1/(k+1)), "
                         "the multi-tenant shape the per-table ledgers "
                         "attribute (ISSUE 18)")
    ap.add_argument("--scenario", default="none",
                    choices=["none", "smoke", "full", "offload",
                             "corruption"],
                    help="scripted chaos schedule to run under the load "
                         "(pegasus_tpu.chaos): smoke = group-worker kill + "
                         "remote fail-point wedge; full = + node "
                         "kill/restart, mid-load split, balancer primary "
                         "move, scheduler token flips, duplication leg "
                         "with cross-cluster digest compare; offload = "
                         "compaction-offload wire wedge + mid-merge "
                         "service kill against a harness-wired offload "
                         "service with every partition placed onto it; "
                         "corruption = scrub.verify fail-point chaos + a "
                         "byte-flipped live SST that must detect → "
                         "quarantine → re-seed with zero wrong reads")
    ap.add_argument("--offload-kill-every", type=float, default=15.0,
                    help="--scenario offload: repeat the mid-merge service "
                         "kill on this period for the whole run (ROADMAP "
                         "offload follow-on (d), the longer soak) instead "
                         "of once; must exceed the kill's 4 s heal window; "
                         "0 = single kill")
    ap.add_argument("--audit-every", type=float, default=5.0,
                    help="seconds between decree-anchored audit rounds "
                         "under the load (0 disables; a final quiesced "
                         "round always runs when enabled)")
    ap.add_argument("--journal", default="",
                    help="write the full event-journal artifact (JSON) here")
    ap.add_argument("--reservoir", type=int, default=8192,
                    help="latency reservoir sample size")
    ap.add_argument("--inject-fault", default="", metavar="POINT=ACTION",
                    help="arm one UNDECLARED fail point on the first node "
                         "at load start (e.g. audit.digest=return() to "
                         "corrupt that node's audit digests) — the "
                         "self-falsification knob: the run must exit 1 "
                         "with the failure named in the journal, proving "
                         "the harness can actually catch what it claims "
                         "to check (requires --scenario)")
    ap.add_argument("--no-audit", action="store_true",
                    help="legacy alias for --audit-every 0")
    return ap.parse_args(argv)


def _build_harness(args, journal):
    """-> (box, dst_box, actors, scenario) for --scenario runs. The
    source onebox serves through partition-group executors (so the
    group-kill leg is a real process kill); the full scenario adds a
    second onebox cluster as the duplication target."""
    from pegasus_tpu.chaos import actors as act
    from pegasus_tpu.chaos import scenario as sc
    from pegasus_tpu.collector.cluster_doctor import ClusterCaller
    from pegasus_tpu.meta import messages as mm
    from pegasus_tpu.meta.meta_server import RPC_CM_ADD_DUPLICATION

    from tools._onebox import Onebox

    box = dst = None
    try:
        if args.scenario == "full":
            dst = Onebox(args.table, partitions=8, n_nodes=3, cluster_id=2)
        # corruption leg (ISSUE 17) serves through PLAIN stubs: the
        # disk-corrupt actor byte-flips a live SST through the node's
        # in-process handle, and group workers are separate processes
        groups = 0 if args.scenario == "corruption" else 2
        box = Onebox(args.table, partitions=8, n_nodes=3,
                     serve_groups=groups,
                     remote_clusters={"chaos-dst": [dst.meta_addr]} if dst
                     else None, cluster_id=1)
        if dst is not None:
            r = box.cluster.ddl(RPC_CM_ADD_DUPLICATION,
                                mm.AddDuplicationRequest(args.table,
                                                         "chaos-dst"),
                                mm.AddDuplicationResponse)
            if r.error:
                raise RuntimeError(f"add_dup failed: {r.error_text}")
            journal.record("dup.added", dupid=r.dupid, remote=dst.meta_addr)
    except BaseException:
        # run_pressure's finally never sees these handles (the assignment
        # from _build_harness did not happen) — stop them here or the
        # half-built clusters' threads + tmpdirs outlive the run
        for b in (box, dst):
            if b is not None:
                b.stop()
        raise
    caller = ClusterCaller([box.meta_addr])

    def alive_nodes():
        return act._alive_nodes(box.cluster, caller)

    # ONE pooled caller shared by every actor: recovery polls run every
    # 0.2 s, and per-poll connections would pile onto a recovering cluster
    actors = {
        sc.A_FAILPOINT: act.FailPointActor(caller, nodes_fn=alive_nodes),
        sc.A_GROUP_KILL: act.GroupWorkerKill(box.cluster, node_index=0),
        sc.A_NODE_KILL: act.NodeKillRestart(box.cluster, node_index=-1,
                                            caller=caller),
        sc.A_SPLIT: act.SplitActor(box.cluster, args.table, caller=caller),
        sc.A_BALANCE: act.BalanceActor(box.cluster, args.table,
                                       caller=caller),
        sc.A_SCHED: act.SchedFlipActor(caller, box.cluster, args.table),
    }
    if args.scenario == "corruption":
        actors[sc.A_DISK_CORRUPT] = act.DiskCorruptActor(
            box.cluster, node_index=0, caller=caller)
    if args.scenario == "offload":
        # rack-scale offload leg (ISSUE 14): one cpu-backend compaction
        # service for the whole onebox rack, every partition placed onto
        # it for the run's duration — the scenario then wedges the wire
        # and hard-kills the service mid-load, and the nodes must ride
        # the offload lane's local-cpu fallback without losing a write
        ctl = _OffloadServiceCtl()
        box.offload_ctl = ctl
        _deliver_offload_placements(caller, box, ctl.address,
                                    ttl_s=args.seconds + 120)
        actors[sc.A_OFFLOAD] = act.OffloadServiceKill(ctl, caller=caller)
    box.chaos_caller = caller   # closed with the box in the run's finally
    box.alive_nodes = alive_nodes   # --inject-fault victim selection
    if args.scenario == "offload":
        # the soak shape (ISSUE 16 satellite): the service kill repeats
        # on --offload-kill-every for the run's whole duration, so a
        # longer --seconds means MORE kill/heal/re-adopt cycles — not
        # one kill followed by minutes of quiet
        scenario = sc.offload_scenario(
            kill_every_s=args.offload_kill_every or None)
    else:
        scenario = sc.SCENARIOS[args.scenario]()
    return box, dst, actors, scenario


class _OffloadServiceCtl:
    """stop()/restart()-able in-process compaction-offload service (the
    OffloadServiceKill actor's handle): restart rebinds the SAME address
    so placement leases delivered before the kill stay valid."""

    def __init__(self):
        import tempfile

        from pegasus_tpu.replication.compact_offload import \
            CompactOffloadService

        self.root = tempfile.mkdtemp(prefix="pegasus_offload_chaos_")
        self.svc = CompactOffloadService(self.root, backend="cpu").start()
        self.address = self.svc.address

    def stop(self):
        self.svc.stop()

    def restart(self):
        from pegasus_tpu.replication.compact_offload import \
            CompactOffloadService

        host, _, port = self.address.rpartition(":")
        self.svc = CompactOffloadService(self.root, host=host,
                                         port=int(port),
                                         backend="cpu").start()

    def close(self):
        import shutil

        try:
            self.svc.stop()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        shutil.rmtree(self.root, ignore_errors=True)


def _deliver_offload_placements(caller, box, svc_addr: str,
                                ttl_s: float) -> None:
    """Hand every alive node a (normal, svc_addr) token for each hosted
    partition — the compact-sched-policy surface the cluster scheduler
    itself uses, with a lease long enough to outlive the run."""
    import json as _json

    from pegasus_tpu.chaos.actors import _cluster_state

    state = _cluster_state(box.cluster, caller) or {}
    decisions = {}
    for app in state.get("apps", {}).values():
        for pc in app.get("partitions", []):
            decisions[f"{app['app_id']}.{pc['pidx']}"] = {
                "policy": "normal", "reasons": ["chaos.offload"],
                "where": svc_addr}
    body = _json.dumps({"ttl_s": ttl_s, "decisions": decisions})
    for node in sorted(a for a, n in state.get("nodes", {}).items()
                       if n.get("alive")):
        try:
            caller.remote_command(node, "compact-sched-policy", [body])
        except Exception:  # noqa: BLE001 - a node that missed the
            continue       # placement simply compacts locally


def _table_list(args):
    """--tables N -> [table, table2, .., tableN] (N=1: just --table)."""
    n = max(1, args.tables)
    return [args.table] + [f"{args.table}{i}" for i in range(2, n + 1)]


def _worker(tid, args, meta_addr, stop_at, stats, stats_lock, lat,
            written, written_lock, windows, journal, table_ops=None):
    from pegasus_tpu.client import MetaResolver, PegasusClient, PegasusError

    rng = random.Random(tid)
    tables = _table_list(args)
    clis = [PegasusClient(MetaResolver([meta_addr], t), timeout=10)
            for t in tables]
    cli = clis[0]
    # skewed tenant mix: table k draws weight 1/(k+1), so the first table
    # dominates and the per-table ledgers have an asymmetry to attribute
    weights = [1.0 / (k + 1) for k in range(len(tables))]
    wsum = sum(weights)
    local_tables = {t: 0 for t in tables}
    per_thread_qps = args.qps / args.threads
    interval = 1.0 / per_thread_qps if per_thread_qps > 0 else 0
    next_fire = time.time()
    local = {"reads": 0, "writes": 0, "scans": 0, "sweeps": 0,
             "sweep_rows": 0, "errors_in_window": 0,
             "errors_steady": 0, "recovered_reads": 0,
             "verify_failures": 0, "not_found": 0}

    def classify_error(t_err, what, detail=""):
        """In-fault-window errors are DECLARED (bounded, allowed);
        steady-state errors fail the run (ISSUE 11 satellite)."""
        if windows is not None and windows.in_window(t_err):
            local["errors_in_window"] += 1
        else:
            local["errors_steady"] += 1
            journal.record("error.steady", op=what, thread=tid,
                           detail=detail)

    def timed(fn, *fargs):
        """One client attempt with its latency sampled. Only FIRST
        attempts go through here — reread()'s retry sleeps are harness
        policy, not server latency, and would inflate p99 by orders of
        magnitude under chaos. An errored attempt still records (its
        duration is real server-observed time)."""
        t0 = time.perf_counter()
        try:
            return fn(*fargs)
        finally:
            lat.add((time.perf_counter() - t0) * 1000)

    def reread(hk, attempts=5, delay=0.2, op=None):
        """-> (ok, value): retry a read past transient routing blips
        before concluding anything about the key. `op` replays a
        NON-point read (the scan leg re-verifies through the same range
        path it failed on); default is the point get."""
        for _ in range(attempts):
            time.sleep(delay)
            try:
                return True, cli.get(hk, b"s") if op is None else op()
            except PegasusError:
                continue
        return False, None

    def verify_row(hk, i, v, was_written):
        """Self-check one read result (shared by the point-get and the
        range-scan legs — byte-identity means the SAME row must come
        back either way)."""
        if v is None:
            if was_written:
                # an acked write must be readable; re-read before
                # declaring it lost (routing may still be settling)
                ok, v2 = reread(hk, attempts=3, delay=0.3)
                if v2 == expected_value(hk):
                    local["recovered_reads"] += 1
                else:
                    local["verify_failures"] += 1
                    journal.record("verify.lost", key=i, thread=tid)
            else:
                local["not_found"] += 1
        elif v != expected_value(hk):
            local["verify_failures"] += 1
            journal.record("verify.corrupt", key=i, thread=tid)

    def range_read(hk):
        """The scan-leg op: a bounded multi_get RANGE ((start, stop]
        resolved through scan_range_batch server-side) that must surface
        the one self-verifying b\"s\" row. Untimed — the first attempt
        wraps it in timed(), rereads replay it raw."""
        _, kvs = cli.multi_get(hk, None, 0, 0, start_sortkey=b"",
                               stop_sortkey=b"t", stop_inclusive=True)
        return kvs.get(b"s")

    def sweep():
        """Full-table unordered-scanner sweep over the primary table:
        every surviving row must self-verify while the chaos schedule
        runs. Values are key-derived and never overwritten, so a
        mismatch is corruption, not a race — but it still gets one
        point-get re-read before it counts (a scanner batch fetched
        mid-failover is retried internally, this guards the residue)."""
        rows = 0
        scanners = []
        try:
            scanners = clis[0].get_unordered_scanners(batch_size=500)
            for sc in scanners:
                for h, s, val in sc:
                    rows += 1
                    if s != b"s" or val == expected_value(h):
                        continue
                    ok, v2 = reread(h, attempts=3, delay=0.3)
                    if v2 != expected_value(h):
                        local["verify_failures"] += 1
                        journal.record("verify.sweep_corrupt",
                                       key=h.decode("latin-1"), thread=tid)
        except PegasusError as e:
            classify_error(journal.now(), "sweep", repr(e))
            return
        finally:
            for sc in scanners:
                sc.close()
        local["sweeps"] += 1
        local["sweep_rows"] += rows

    next_sweep = time.time() + 10.0 if (tid == 0 and args.scan_pct) \
        else float("inf")

    while time.time() < stop_at:
        now = time.time()
        if now >= next_sweep:
            sweep()
            next_sweep = time.time() + 10.0
            next_fire = time.time()  # don't burst-repay the sweep time
        if interval and now < next_fire:
            time.sleep(min(interval, next_fire - now))
            continue
        next_fire += interval
        i = rng.randrange(args.key_space)
        if len(tables) == 1:
            hk = b"pres%07d" % i
        else:
            # distinct per-table key prefix: self-verification (value
            # derived from the FULL key) stays sound across tenants
            r = rng.random() * wsum
            t_idx = 0
            while t_idx < len(tables) - 1 and r > weights[t_idx]:
                r -= weights[t_idx]
                t_idx += 1
            cli = clis[t_idx]
            hk = b"%s:pres%07d" % (tables[t_idx].encode(), i)
            local_tables[tables[t_idx]] += 1
        roll = rng.randrange(100)
        if roll < args.read_pct:
            # snapshot BEFORE the read: a write completing between
            # the get and a later check would fake a lost write
            with written_lock:
                was_written = hk in written
            try:
                v = timed(cli.get, hk, b"s")
            except PegasusError as e:
                # re-read-verify before counting anything: a failover
                # blip is not a lost write. Only a read that keeps
                # erroring counts as an error at the ORIGINAL instant.
                t_err = journal.now()
                ok, v = reread(hk)
                if not ok:
                    classify_error(t_err, "get", repr(e))
                    continue
                local["recovered_reads"] += 1
            local["reads"] += 1
            verify_row(hk, i, v, was_written)
        elif roll < args.read_pct + args.scan_pct:
            with written_lock:
                was_written = hk in written
            try:
                v = timed(range_read, hk)
            except PegasusError as e:
                t_err = journal.now()
                ok, v = reread(hk, op=lambda: range_read(hk))
                if not ok:
                    classify_error(t_err, "multi_get_range", repr(e))
                    continue
                local["recovered_reads"] += 1
            local["scans"] += 1
            verify_row(hk, i, v, was_written)
        else:
            try:
                timed(cli.set, hk, b"s", expected_value(hk))
            except PegasusError as e:
                classify_error(journal.now(), "set", repr(e))
                continue
            with written_lock:
                written.add(hk)
            local["writes"] += 1
    for c in clis:
        c.close()
    with stats_lock:
        for k, v in local.items():
            stats[k] += v
        if table_ops is not None:
            for t, v in local_tables.items():
                table_ops[t] = table_ops.get(t, 0) + v


def run_pressure(argv=None) -> int:
    """The whole run; returns the process exit code (importable for
    tests — main() wraps it)."""
    args = _parse_args(argv)
    if args.no_audit:
        args.audit_every = 0.0
    if args.scenario != "none" and args.meta:
        print("pressure_test: --scenario needs the self-booted onebox "
              "(the fault actors hold cluster handles); drop --meta",
              file=sys.stderr)
        return 2
    if args.inject_fault and args.scenario == "none":
        print("pressure_test: --inject-fault requires --scenario "
              "(it arms over the harness's remote-command caller)",
              file=sys.stderr)
        return 2

    from pegasus_tpu.chaos.journal import EventJournal, FaultWindows
    from pegasus_tpu.chaos.scenario import ScenarioRunner
    from pegasus_tpu.collector.cluster_doctor import (
        AuditRounds, run_cluster_doctor, run_cross_cluster_audit)

    journal = EventJournal()
    windows = FaultWindows(journal)
    box = dst = runner = None
    meta_addr = args.meta
    try:
        if args.scenario != "none":
            box, dst, actors, scenario = _build_harness(args, journal)
            meta_addr = box.meta_addr
            runner = ScenarioRunner(scenario, actors, journal,
                                    windows=windows)
        elif not args.meta:
            from tools._onebox import Onebox

            box = Onebox(args.table, partitions=8)
            meta_addr = box.meta_addr
        tables = _table_list(args)
        if box is not None:
            for extra in tables[1:]:
                box.cluster.create(extra, partitions=8).close()

        stats = {"reads": 0, "writes": 0, "scans": 0, "sweeps": 0,
                 "sweep_rows": 0, "errors_in_window": 0,
                 "errors_steady": 0, "recovered_reads": 0,
                 "verify_failures": 0, "not_found": 0}
        stats_lock = threading.Lock()
        lat = LatencyReservoir(cap=args.reservoir)
        written = set()
        written_lock = threading.Lock()
        table_ops = {}  # per-table op counts (guarded by stats_lock)

        # flight recorder (ISSUE 12): the FIRST named failure of the run
        # captures an incident artifact AT failure time (the nodes' event
        # rings + metric history still hold the lead-up), and the
        # artifact rides the journal. One capture per run: later
        # failures of the same run share the same recorded past.
        incident_box = [None]
        incident_lock = threading.Lock()

        def _capture_on_fail(ev):
            # serialized: concurrent first failures (a node kill breaking
            # several reads at once) must still yield ONE capture; a
            # failed capture releases the latch so a later failure retries
            with incident_lock:
                if incident_box[0] is not None:
                    return
                from pegasus_tpu.collector.flight_recorder import RECORDER

                inc = RECORDER.capture(
                    [meta_addr], reason=f"chaos failure {ev['failure']}",
                    trigger="chaos")
                incident_box[0] = {"id": inc["id"], "path": inc["path"],
                                   "first_cause": inc["first_cause"]}
            journal.record("incident.captured", **incident_box[0])

        journal.on_fail = _capture_on_fail

        audits = None
        if args.audit_every > 0:
            audits = AuditRounds([meta_addr], apps=tables,
                                 every_s=args.audit_every,
                                 wait_s=min(5.0, args.audit_every),
                                 journal=journal).start()
        elif args.scenario in ("offload", "corruption"):
            # these legs ALWAYS conclude with one quiesced audit round,
            # even under --audit-every 0: a run that survived the faults
            # but never proved the digests match proved nothing — for
            # the corruption leg the conclusive mismatch-free round IS
            # the zero-wrong-reads claim. The huge cadence parks the
            # loop on its stop event; stop(final_round=True) below runs
            # the single post-quiesce round.
            audits = AuditRounds([meta_addr], apps=tables,
                                 every_s=3600.0, wait_s=5.0,
                                 journal=journal).start()
        if args.inject_fault:
            # UNDECLARED corruption on the first node — no fault window,
            # no heal: the audits/classifier must catch it and fail the
            # run, or the harness's green runs mean nothing
            point, _, action = args.inject_fault.partition("=")
            victim = box.alive_nodes()[0]
            reply = box.chaos_caller.remote_command(victim, "set-fail-point",
                                                    [point, action])
            if not (reply or "").lstrip().startswith("{"):
                # a rejected arming (bad name/action) would otherwise let
                # the run pass its self-falsification check with NO fault
                # planted — the journal would lie
                print(f"pressure_test: --inject-fault rejected: {reply}",
                      file=sys.stderr)
                return 2
            journal.record("fault.injected", point=point, action=action,
                           node=victim, declared=False)
        journal.record("load.start", qps=args.qps, seconds=args.seconds,
                       threads=args.threads, read_pct=args.read_pct,
                       scan_pct=args.scan_pct, scenario=args.scenario)
        t_start = time.time()
        stop_at = t_start + args.seconds
        if runner is not None:
            runner.start(args.seconds)
        from pegasus_tpu.runtime.tasking import spawn_thread

        threads = [spawn_thread(
            _worker, t, args, meta_addr, stop_at, stats, stats_lock, lat,
            written, written_lock,
            windows if args.scenario != "none" else None, journal,
            table_ops, name=f"pressure-{t}", start=False)
            for t in range(args.threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.time() - t_start
        journal.record("load.done", elapsed_s=round(elapsed, 1))
        if runner is not None:
            # every armed fault heals + verifies recovery (may run past
            # the load window); a wedged actor is bounded by its own
            # recovery deadline, so the join is finite
            runner.join(timeout=180)

        # ---- conclusions: audit rounds (final quiesced round), the
        # cross-cluster digest compare, the final doctor verdict
        audit_summary = None
        if audits is not None:
            audit_summary = audits.stop(final_round=True)
            if audit_summary["mismatches"]:
                pass  # already journal.fail'd per mismatch by AuditRounds
            elif audit_summary["conclusive"] == 0:
                journal.fail("audit.vacuous",
                             detail="zero conclusive audit rounds — zero "
                                    "mismatches proves nothing",
                             rounds=audit_summary["rounds"])
        xcluster = None
        if dst is not None:
            # retry while INCONCLUSIVE (match=None) only: right after the
            # node-kill leg a replica can still be mid-learn, which makes
            # a single audit attempt vacuous (not wrong) — writes are
            # quiesced, so waiting out the learn and re-auditing is
            # sound. A real mismatch (match=False) is never retried.
            for attempt in range(3):
                xcluster = run_cross_cluster_audit(
                    [meta_addr], [dst.meta_addr], args.table)
                if xcluster["match"] is not None:
                    break
                journal.record("cross_cluster.retry", attempt=attempt,
                               inconclusive=xcluster["inconclusive"])
                time.sleep(5.0)
            journal.record("cross_cluster.audit", match=xcluster["match"],
                           src=xcluster["src"], dst=xcluster["dst"],
                           anchors=xcluster["anchors"])
            if xcluster["match"] is not True:
                journal.fail("cross_cluster.digest",
                             match=xcluster["match"],
                             inconclusive=xcluster["inconclusive"],
                             mismatches=xcluster["mismatches"])
        doctor = None
        if args.scenario != "none":
            doctor = run_cluster_doctor([meta_addr])
            journal.record("doctor.final", verdict=doctor["verdict"],
                           causes=[c["cause"] for c in doctor["causes"]])
            if doctor["verdict"] != "healthy":
                journal.fail("doctor.unhealthy", verdict=doctor["verdict"],
                             causes=[c["cause"] for c in doctor["causes"]])

        # final quiesced fsck sweep (ISSUE 17): every surviving replica's
        # on-disk state must verify clean — a corruption the run's audits
        # missed (or one planted and never healed) fails the run here.
        # Engines are still live (background compaction can land files
        # between the walk and the verify), so transient error sets get
        # one re-check before they count.
        if box is not None:
            from tools.fsck import find_data_dirs, fsck_data_dir

            fsck_errors, ndirs = [], 0
            for attempt in range(2):
                fsck_errors, ndirs = [], 0
                for stub in list(box.cluster.stubs):
                    for d in find_data_dirs(stub.root):
                        ndirs += 1
                        fsck_errors.extend(
                            f for f in fsck_data_dir(d)
                            if f["severity"] == "error"
                            and os.path.exists(f["path"]))
                if not fsck_errors:
                    break
                time.sleep(2.0)
            journal.record("fsck.final", dirs=ndirs,
                           errors=len(fsck_errors))
            if fsck_errors:
                journal.fail("fsck.corruption", count=len(fsck_errors),
                             first=f"{fsck_errors[0]['path']}: "
                                   f"{fsck_errors[0]['detail']}")

        if stats["verify_failures"]:
            journal.fail("verify.lost_acked_writes",
                         count=stats["verify_failures"])
        if stats["errors_steady"]:
            journal.fail("errors.steady_state",
                         count=stats["errors_steady"],
                         detail="errors outside any declared fault window")

        total_ops = stats["reads"] + stats["writes"] + stats["scans"]
        failures = journal.failures
        detail = {**stats, "elapsed_s": round(elapsed, 1),
                  "avg_ms": lat.avg(), "p95_ms": lat.percentile(0.95),
                  "p99_ms": lat.percentile(0.99),
                  "lat_sampled": min(lat.count, lat.cap),
                  "audit_rounds": audit_summary,
                  "fault_windows": windows.bounds(),
                  "failures": [f["failure"] for f in failures]}
        if len(tables) > 1:
            detail["table_ops"] = dict(sorted(table_ops.items()))
        if xcluster is not None:
            detail["cross_cluster"] = {
                k: xcluster[k] for k in ("match", "src", "dst", "dupid")
                if k in xcluster}
        if doctor is not None:
            detail["doctor"] = doctor["verdict"]
        if incident_box[0] is not None:
            detail["incident"] = incident_box[0]
        print(json.dumps({
            "metric": f"pressure test achieved qps (target {args.qps}, "
                      f"{args.read_pct}% reads, {args.scan_pct}% scans, "
                      f"{args.threads} threads, "
                      f"scenario {args.scenario})",
            "value": round(total_ops / elapsed, 1),
            "unit": "ops/s",
            "detail": detail,
        }), flush=True)
        if args.journal:
            journal.write(args.journal)
        for f in failures:
            print(f"pressure_test: FAILED: {f['failure']}: "
                  f"{ {k: v for k, v in f.items() if k not in ('kind', 'failure')} }",
                  file=sys.stderr)
        return 1 if failures else 0
    finally:
        if runner is not None:
            runner.stop()
        for b in (box, dst):
            if b is not None:
                if getattr(b, "offload_ctl", None) is not None:
                    b.offload_ctl.close()
                if getattr(b, "chaos_caller", None) is not None:
                    b.chaos_caller.close()
                b.stop()


def main():
    sys.exit(run_pressure())


if __name__ == "__main__":
    main()
