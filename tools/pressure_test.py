"""Pressure test: sustained target-QPS load with self-checking data.

The reference's src/test/pressure_test tier: a long-running load generator
holding a TARGET qps against a cluster (onebox here; point --meta at any
cluster) with a configurable op mix, writing SELF-CHECKING rows (value
derived from key) so every read verifies itself, and reporting achieved
qps + latency percentiles + verification failures.

Usage:
    python tools/pressure_test.py [--meta host:port] [--table t]
        [--qps 500] [--seconds 30] [--threads 4] [--read-pct 50]
(no --meta: boots its own in-process onebox)
"""

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def expected_value(key: bytes) -> bytes:
    import hashlib

    return hashlib.md5(key).hexdigest().encode()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--meta", default="")
    ap.add_argument("--table", default="pressure")
    ap.add_argument("--qps", type=int, default=500)
    ap.add_argument("--seconds", type=float, default=30.0)
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--read-pct", type=int, default=50)
    ap.add_argument("--key-space", type=int, default=100_000)
    ap.add_argument("--no-audit", action="store_true",
                    help="skip the mid-run decree-anchored consistency "
                         "audit (on by default; a digest mismatch fails "
                         "the run like a verify failure)")
    args = ap.parse_args()

    from pegasus_tpu.client import MetaResolver, PegasusClient, PegasusError

    from tools._onebox import resolve_cluster

    meta_addr, cluster = resolve_cluster(args.meta, args.table, 8)
    try:

        per_thread_qps = args.qps / args.threads
        stop_at = time.time() + args.seconds
        stats_lock = threading.Lock()
        stats = {"reads": 0, "writes": 0, "errors": 0, "verify_failures": 0,
                 "not_found": 0}
        lat_ms = []
        written = set()
        written_lock = threading.Lock()

        def worker(tid):
            rng = random.Random(tid)
            cli = PegasusClient(MetaResolver([meta_addr], args.table), timeout=10)
            interval = 1.0 / per_thread_qps if per_thread_qps > 0 else 0
            next_fire = time.time()
            local = {k: 0 for k in stats}
            local_lat = []
            while time.time() < stop_at:
                now = time.time()
                if interval and now < next_fire:
                    time.sleep(min(interval, next_fire - now))
                    continue
                next_fire += interval
                i = rng.randrange(args.key_space)
                hk = b"pres%07d" % i
                t0 = time.perf_counter()
                try:
                    if rng.randrange(100) < args.read_pct:
                        # snapshot BEFORE the read: a write completing between
                        # the get and a later check would fake a lost write
                        with written_lock:
                            was_written = i in written
                        v = cli.get(hk, b"s")
                        local["reads"] += 1
                        if v is None:
                            if was_written:
                                local["verify_failures"] += 1
                            else:
                                local["not_found"] += 1
                        elif v != expected_value(hk):
                            local["verify_failures"] += 1
                    else:
                        cli.set(hk, b"s", expected_value(hk))
                        with written_lock:
                            written.add(i)
                        local["writes"] += 1
                except PegasusError:
                    local["errors"] += 1
                local_lat.append((time.perf_counter() - t0) * 1000)
            cli.close()
            with stats_lock:
                for k, v in local.items():
                    stats[k] += v
                lat_ms.extend(local_lat)

        t_start = time.time()
        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(args.threads)]
        for t in threads:
            t.start()
        # consistency audit UNDER the load (ISSUE 8): partway through the
        # run, every replica digests its state at the same applied decree;
        # a mismatch fails the run exactly like a verify failure — the
        # pass criterion the production-sim scenario builds on
        audit = None
        if not args.no_audit:
            from pegasus_tpu.collector.cluster_doctor import \
                run_cluster_audit

            time.sleep(min(2.0, args.seconds / 2))
            audit = run_cluster_audit([meta_addr], apps=[args.table],
                                      wait_s=20.0)
            audit.pop("digests", None)
        for t in threads:
            t.join()
        elapsed = time.time() - t_start
        lat_ms.sort()
        total_ops = stats["reads"] + stats["writes"]

        def pct(p):
            return round(lat_ms[min(len(lat_ms) - 1,
                                    int(len(lat_ms) * p))], 2) if lat_ms else 0

        print(json.dumps({
            "metric": f"pressure test achieved qps (target {args.qps}, "
                      f"{args.read_pct}% reads, {args.threads} threads)",
            "value": round(total_ops / elapsed, 1),
            "unit": "ops/s",
            "detail": {**stats, "elapsed_s": round(elapsed, 1),
                       "avg_ms": round(sum(lat_ms) / max(1, len(lat_ms)), 2),
                       "p95_ms": pct(0.95), "p99_ms": pct(0.99),
                       "audit": audit},
        }), flush=True)

    finally:
        if cluster is not None:
            cluster.stop()
    audit_failed = bool(audit and audit.get("mismatches"))
    if audit_failed:
        print(f"pressure_test: consistency audit FAILED: "
              f"{audit['mismatches']}", file=sys.stderr)
    elif audit is not None and len(audit.get("ok", [])) \
            != audit.get("partitions", 0):
        # zero mismatches without full coverage is not a pass — say so
        # (only a real mismatch fails the run, per the audit contract)
        print("pressure_test: consistency audit inconclusive for "
              f"{audit.get('partitions', 0) - len(audit.get('ok', []))} "
              "partition(s) — zero mismatches is vacuous",
              file=sys.stderr)
    sys.exit(1 if stats["verify_failures"] or stats["errors"]
             or audit_failed else 0)


if __name__ == "__main__":
    main()
