"""Shared self-booting onebox for the tools/ benchmark harnesses."""

import os
import pathlib
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


class Onebox:
    """In-process 1-meta/3-replica cluster with one table, cleaned up on
    stop() (or `with Onebox(...) as box:`); `meta_addr` is the routing
    entry point."""

    def __init__(self, table: str, partitions: int = 8, n_nodes: int = 3,
                 serve_groups: int = 0, replicas: int = 3,
                 remote_clusters: dict = None, cluster_id: int = 1,
                 fd_grace_seconds: float = 60, create: bool = True):
        from tests.test_satellites import MiniCluster

        self._tmp = tempfile.TemporaryDirectory(prefix="pegasus_tool_")
        self.cluster = MiniCluster(pathlib.Path(self._tmp.name),
                                   n_nodes=n_nodes, serve_groups=serve_groups,
                                   remote_clusters=remote_clusters,
                                   cluster_id=cluster_id,
                                   fd_grace_seconds=fd_grace_seconds)
        if create:
            self.cluster.create(table, partitions=partitions,
                                replicas=replicas).close()
        self.meta_addr = self.cluster.meta_addr

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def stop(self):
        self.cluster.stop()
        self._tmp.cleanup()


def resolve_cluster(meta: str, table: str, partitions: int = 8):
    """-> (meta_addr, onebox_or_None): boot an onebox when no --meta given."""
    if meta:
        return meta, None
    box = Onebox(table, partitions=partitions)
    return box.meta_addr, box
