"""redis-benchmark-style lanes through the RESP proxy (reference
redis_proxy over pegasus, ecosystem row SURVEY §2.6): SET / GET / INCR
driven over raw RESP sockets against a proxy backed by a live onebox,
one JSON line per lane.

    python tools/redis_bench.py [--ops 10000] [--threads 1,4]
"""

import argparse
import json
import os
import random
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def resp_cmd(*args) -> bytes:
    out = b"*%d\r\n" % len(args)
    for a in args:
        a = a if isinstance(a, bytes) else str(a).encode()
        out += b"$%d\r\n%s\r\n" % (len(a), a)
    return out


def read_reply(f):
    line = f.readline().rstrip(b"\r\n")
    t, rest = line[:1], line[1:]
    if t in (b"+", b"-", b":"):
        return rest
    if t == b"$":
        n = int(rest)
        if n < 0:
            return None
        data = f.read(n + 2)[:-2]
        return data
    if t == b"*":
        return [read_reply(f) for _ in range(int(rest))]
    raise ValueError(f"bad RESP type {t!r}")


def run_lane(name, addr, n_ops, n_threads, value):
    lats = [[] for _ in range(n_threads)]
    errors = [0] * n_threads

    def worker(tid):
        rng = random.Random(tid * 31)
        sock = socket.create_connection(addr, timeout=15)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        f = sock.makefile("rwb")
        for i in range(n_ops):
            key = b"rb%02d%06d" % (tid, rng.randrange(n_ops))
            if name == "SET":
                cmd = resp_cmd(b"SET", key, value)
            elif name == "GET":
                cmd = resp_cmd(b"GET", key)
            else:
                cmd = resp_cmd(b"INCR", b"ctr%02d" % tid)
            t0 = time.perf_counter()
            f.write(cmd)
            f.flush()
            reply = read_reply(f)
            lats[tid].append((time.perf_counter() - t0) * 1e6)
            if isinstance(reply, bytes) and reply.startswith(b"ERR"):
                errors[tid] += 1
        sock.close()

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    flat = sorted(x for lane in lats for x in lane)
    total = len(flat)
    return {"benchmark": f"redis_{name}", "threads": n_threads,
            "qps": round(total / elapsed, 1),
            "avg_us": round(sum(flat) / max(1, total), 1),
            "p99_us": round(flat[min(total - 1, int(total * .99))], 1),
            "ops": total, "errors": sum(errors)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--meta", default="")
    ap.add_argument("--ops", type=int, default=10_000)
    ap.add_argument("--threads", default="1")
    ap.add_argument("--value-size", type=int, default=100)
    ns = ap.parse_args()

    from pegasus_tpu.client import MetaResolver, PegasusClient
    from pegasus_tpu.redis_proxy import RedisProxy

    from tools._onebox import resolve_cluster

    meta_addr, box = resolve_cluster(ns.meta, "redisbench", 8)
    cli = PegasusClient(MetaResolver([meta_addr], "redisbench"), timeout=15)
    proxy = RedisProxy(cli).start()
    value = os.urandom(ns.value_size)
    try:
        for n_threads in (int(t) for t in ns.threads.split(",")):
            for lane in ("SET", "GET", "INCR"):
                print(json.dumps(run_lane(lane, proxy.address, ns.ops,
                                          n_threads, value)), flush=True)
    finally:
        proxy.stop()
        cli.close()
        if box is not None:
            box.stop()


if __name__ == "__main__":
    main()
