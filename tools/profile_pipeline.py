"""Per-piece timing of the TPU compaction pipeline on the live chip.

Breaks the _pipeline_body cost into: merge tree, dedup mask, aux
gathers+filter, cumsum, final scatter, and the survivor-index download,
each timed as its own jitted call with block_until_ready. Run directly:

    python tools/profile_pipeline.py [N]
"""

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def t(fn, *args, reps=3):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best, out


def main():
    n_total = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    from pegasus_tpu.base.utils import enable_compile_cache

    enable_compile_cache(REPO)
    import jax
    import jax.numpy as jnp

    import bench as B
    from pegasus_tpu.engine.block import KVBlock
    from pegasus_tpu.ops.compact import (CompactOptions, TpuBackend, pack_runs,
                                         _pow2ceil)

    print("platform:", jax.devices()[0], flush=True)
    n_runs = 4
    per = n_total // n_runs
    t0 = time.perf_counter()
    runs = [B.presort_run(B.make_run(per, 100, seed=s,
                                     key_space=max(1, n_total // 2)))
            for s in range(n_runs)]
    opts = CompactOptions(backend="tpu", now=100, bottommost=True,
                          runs_sorted=True)
    packed = pack_runs(runs, opts, need_sbytes=True)
    concat = KVBlock.concat(runs)
    print(f"fill+pack: {time.perf_counter()-t0:.2f}s", flush=True)

    backend = TpuBackend()
    prep = backend.prepare(packed)
    nk = prep.w + (2 if prep.has_rank else 1)

    # --- stage 1: merge tree alone (no dedup/filter/scatter) ---
    from pegasus_tpu.ops.device_sort import merge_two_sorted

    def merge_tree(run_cols):
        items = []
        for i, rc in enumerate(run_cols):
            *kcols, klen, idx = rc
            kp = (klen << jnp.uint32(8)) | jnp.uint32(i)
            items.append((prep.padded_lens[i], list(kcols) + [kp, idx]))
        pad_fill = tuple([0xFFFFFFFF] * nk + [np.int32(-1)])
        while len(items) > 1:
            items.sort(key=lambda x: x[0])
            (la, a), (lb, b) = items[0], items[1]
            merged = merge_two_sorted(a, b, nk, pad_fill)
            lm = _pow2ceil(la + lb)
            if lm > la + lb:
                merged = [c[: la + lb] for c in merged]
            items = items[2:] + [(la + lb, merged)]
        return items[0][1]

    jtree = jax.jit(merge_tree)
    s, cols = t(jtree, prep.run_cols)
    print(f"merge tree: {s:.3f}s", flush=True)
    cols = list(cols)

    # --- stage 2: dedup mask + aux gather + filter mask ---
    def mask_of(cols, aux):
        idx = cols[-1]
        kp = cols[nk - 1]
        key_eq = cols[: nk - 1] + [kp >> jnp.uint32(8)]
        import functools

        same_tail = functools.reduce(
            jnp.logical_and, [c[1:] == c[:-1] for c in key_eq])
        same = jnp.concatenate([jnp.zeros(1, dtype=bool), same_tail])
        valid = idx >= 0
        keep = valid & ~same
        safe = jnp.maximum(idx, 0)
        expire = jnp.take(aux[0], safe)
        deleted = jnp.take(aux[1], safe)
        hash32 = jnp.take(aux[2], safe)
        expired = (expire > 0) & (expire <= jnp.uint32(100))
        # hash32 returned (not just gathered) so XLA cannot dead-code the
        # third aux gather the real _pipeline_body always pays
        return keep & ~expired & ~deleted, hash32

    jmask = jax.jit(mask_of)
    s, (keep, _h) = t(jmask, cols, prep.aux)
    print(f"dedup+filter mask: {s:.3f}s", flush=True)

    # --- stage 3a: scatter compaction (current) ---
    def compact_scatter(keep, idx):
        n = idx.shape[0]
        pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        count = pos[-1] + 1
        tgt = jnp.where(keep, pos, n)
        out = jnp.full((n,), -1, jnp.int32).at[tgt].set(idx, mode="drop")
        return out, count

    jscat = jax.jit(compact_scatter)
    s, (out_idx, count) = t(jscat, keep, cols[-1])
    print(f"scatter compact: {s:.3f}s (count={int(count)})", flush=True)

    # --- stage 3b: sort-based compaction alternative ---
    def compact_sort(keep, idx):
        n = idx.shape[0]
        pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        count = pos[-1] + 1
        # output slot for each input: pos where kept, else n (tail)
        key = jnp.where(keep, pos, n).astype(jnp.int32)
        # stable ascending sort of (key, idx): kept rows land at [0, count)
        order = jnp.argsort(key, stable=True)
        return jnp.take(idx, order), count

    jsort = jax.jit(compact_sort)
    s, (out2, count2) = t(jsort, keep, cols[-1])
    print(f"sort compact:    {s:.3f}s", flush=True)

    # --- stage 3c: searchsorted-based compaction alternative ---
    def compact_searchsorted(keep, idx):
        n = idx.shape[0]
        csum = jnp.cumsum(keep.astype(jnp.int32))
        count = csum[-1]
        q = jnp.arange(n, dtype=jnp.int32) + 1
        j = jnp.searchsorted(csum, q, side="left")
        out = jnp.take(idx, jnp.minimum(j, n - 1))
        out = jnp.where(q <= count, out, -1)
        return out, count

    jss = jax.jit(compact_searchsorted)
    s, (out3, count3) = t(jss, keep, cols[-1])
    print(f"searchsorted compact: {s:.3f}s", flush=True)

    a = np.asarray(out_idx[: int(count)])
    b = np.asarray(out2[: int(count2)])
    c3 = np.asarray(out3[: int(count3)])
    print("compact variants equal:", np.array_equal(a, b), np.array_equal(a, c3),
          flush=True)

    # --- stage 4: index download (sync vs chunked-async) ---
    cnt = int(count)
    t0 = time.perf_counter()
    idx_host = np.asarray(out_idx[:cnt])
    print(f"index download sync ({cnt*4/1e6:.0f} MB): "
          f"{time.perf_counter()-t0:.3f}s", flush=True)

    dl = out_idx[:cnt]
    t0 = time.perf_counter()
    try:
        dl.copy_to_host_async()
        print(f"copy_to_host_async returned in {time.perf_counter()-t0:.3f}s",
              flush=True)
    except AttributeError:
        print("copy_to_host_async NOT AVAILABLE", flush=True)
    t0 = time.perf_counter()
    _ = np.asarray(dl)
    print(f"asarray after async: {time.perf_counter()-t0:.3f}s", flush=True)

    # --- stage 5: host gather variants ---
    kl0, vl0 = int(concat.key_len[0]), int(concat.val_len[0])
    n = concat.n
    key2d = concat.key_arena.reshape(n, kl0)
    val2d = concat.val_arena.reshape(n, vl0)
    t0 = time.perf_counter()
    _k = key2d[idx_host]
    _v = val2d[idx_host]
    print(f"host gather numpy 2D fancy: {time.perf_counter()-t0:.3f}s "
          f"({(_k.nbytes+_v.nbytes)/1e9:.2f} GB out)", flush=True)

    from pegasus_tpu import native

    if native.available():
        t0 = time.perf_counter()
        idx64 = idx_host.astype(np.int64)
        ko, _ = native.gather_arena(concat.key_arena, concat.key_off,
                                    concat.key_len, idx64)
        vo, _ = native.gather_arena(concat.val_arena, concat.val_off,
                                    concat.val_len, idx64)
        print(f"host gather native arena: {time.perf_counter()-t0:.3f}s",
              flush=True)

    t0 = time.perf_counter()
    from pegasus_tpu.ops.compact import gather_device_survivors

    out = gather_device_survivors(concat, out_idx, cnt)
    print(f"gather_device_survivors (chunked overlap): "
          f"{time.perf_counter()-t0:.3f}s", flush=True)


if __name__ == "__main__":
    main()
