"""Build-freshness gate for the native libraries (ISSUE 20).

The runtime loaders (pegasus_tpu/native/__init__.py) rebuild lazily on
an mtime check, but only at FIRST use in a process — a test session that
imports the cached .so via an already-running server process, or a
source edit racing an import, can silently exercise a stale binary.
`ensure()` makes staleness impossible at one choke point: it compares
each native source against its artifact and rebuilds with the plain
in-image compiler (no pip, no setup.py) BEFORE anything imports
pegasus_tpu. tests/conftest.py calls it at collection time, so tier-1
always runs against the current C.

A missing compiler degrades LOUDLY to the pure-Python twins (the
loaders return None and every native call site has a byte-identical
fallback) — the message names what was skipped so a "why is the bench
slow" hunt starts in the right place.
"""

import os
import subprocess
import sys
import sysconfig

_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    os.pardir, "pegasus_tpu", "native")
_DIR = os.path.abspath(_DIR)


def _ext_suffix() -> str:
    return sysconfig.get_config_var("EXT_SUFFIX") or ".so"


def _targets() -> list:
    """[(label, source, artifact, build argv), ...] for every native lib."""
    inc = sysconfig.get_paths()["include"]
    fc_src = os.path.join(_DIR, "fastcodec.c")
    fc_so = os.path.join(_DIR, "fastcodec" + _ext_suffix())
    ho_src = os.path.join(_DIR, "hostops.cpp")
    ho_so = os.path.join(_DIR, "libhostops.so")
    return [
        ("fastcodec", fc_src, fc_so,
         ["gcc", "-O2", "-shared", "-fPIC", f"-I{inc}"]),
        ("hostops", ho_src, ho_so,
         ["g++", "-O3", "-shared", "-fPIC"]),
    ]


def _build(src: str, out: str, cc: list) -> str:
    """Atomic rebuild (tmp + os.replace, same discipline as the runtime
    loaders: a crashed compiler must never leave a corrupt artifact that
    is fresher than its source). -> status string."""
    tmp = f"{out}.{os.getpid()}.tmp"

    def drop_tmp():
        try:
            os.unlink(tmp)
        except OSError:
            pass

    try:
        res = subprocess.run(cc + ["-o", tmp, src], capture_output=True,
                             timeout=180)
    except FileNotFoundError:
        return "missing-compiler"
    except (OSError, subprocess.TimeoutExpired):
        drop_tmp()
        return "build-failed"
    if res.returncode != 0:
        drop_tmp()
        sys.stderr.write(res.stderr.decode(errors="replace")[-2000:] + "\n")
        return "build-failed"
    try:
        os.replace(tmp, out)
    except OSError:
        drop_tmp()
        return "build-failed"
    return "rebuilt"


def ensure(quiet: bool = False) -> dict:
    """Rebuild every stale native artifact. -> {label: status} with
    status in {fresh, rebuilt, missing-compiler, build-failed,
    missing-source}. Never raises: any failure means the pure-Python
    twins serve (loudly, unless quiet)."""
    statuses = {}
    for label, src, out, cc in _targets():
        if not os.path.exists(src):
            statuses[label] = "missing-source"
            continue
        try:
            fresh = (os.path.exists(out)
                     and os.path.getmtime(out) >= os.path.getmtime(src))
        except OSError:
            fresh = False
        if fresh:
            statuses[label] = "fresh"
            continue
        statuses[label] = _build(src, out, cc)
        if statuses[label] in ("missing-compiler", "build-failed") \
                and not quiet:
            print(f"[build-native] {label}: {statuses[label]} — the "
                  f"PURE-PYTHON fallback will serve (slower, "
                  f"byte-identical); fix the toolchain to re-enable the "
                  f"native path", file=sys.stderr, flush=True)
    return statuses


def main() -> int:
    statuses = ensure()
    for label, status in sorted(statuses.items()):
        print(f"{label}: {status}")
    bad = [s for s in statuses.values()
           if s in ("build-failed", "missing-source")]
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main())
